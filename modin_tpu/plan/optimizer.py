"""graftopt: one adaptive, cost-based optimizer over graftplan's IR.

Before this module the engine held FIVE independent execution-strategy
deciders — kernel ``decide()`` (device/host), ``decide_layout``
(local/sharded), ``decide_compile`` (fused/staged), ``decide_residency``
(resident/windowed), and graftview's zero-cost artifact leg — each with
its own crossover logic, consulted at its own layer, at its own time.
Jointly-wrong choices were structural: a plan that will stream should not
donate its inputs; a windowed tail can never amortize a whole-plan
compile; a storming fused signature keeps paying traces the staged
kernels would skip.  Xorbits (arXiv 2401.00865) automates exactly this
chunking decision at runtime and Dias (arXiv 2303.16146) shows dynamic
rewriting is profitable *mid-query* — this module is both halves:

- :func:`choose` runs ONCE per plan materialization and annotates every
  node with a :class:`NodeStrategy` — estimated rows/bytes/seconds from
  the calibrated coefficients (kernel-router table via
  :func:`~modin_tpu.ops.router.calibration_peek`, graftcost substrate
  peaks, PERF_HISTORY priors) plus the jointly-consistent strategy legs.
- the existing routers stay the per-leg cost providers AND the live
  deciders: each ``decide_*`` offers its verdict through the
  ``router._opt_consult`` hook, and the optimizer overrides it only where
  the plan-time joint constraints or a mid-query re-plan disagree.  With
  ``MODIN_TPU_OPT=Off`` the hook is None and behavior is bit-for-bit the
  pre-graftopt five-router engine, with zero optimizer allocations
  (:func:`opt_alloc_count` asserts exactly that, graftscope-style).
- **mid-query re-planning**: lowering feeds each node's measured wall
  back through :func:`observe`; when a node overshoots its estimate by
  ``MODIN_TPU_OPT_REPLAN_FACTOR`` the not-yet-lowered plan segment is
  re-chosen with the measured/estimated ratio folded in as a correction
  on the calibrated device-side coefficients (``wall_divergence``).  Live
  ledger pressure contradicting a planned resident leg re-plans the tail
  windowed (``ledger_pressure``); a storming fused signature re-plans
  staged (``compile_storm``).  Every re-plan is metered
  (``opt.replan.*``), span-tagged (``opt.replan``), recorded on the
  strategy set for EXPLAIN, and fires at most once per (node, trigger).

The deterministic row floors (``*_MIN_ROWS``) and forced modes always
win: the consult hook is only offered verdicts whose reason is a genuine
cost-model/auto outcome, so tests and bench legs that pin a side, and
tiny unit-test frames, never observe the optimizer at all.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import spans as graftscope
from modin_tpu.ops import calibration as calstore
from modin_tpu.ops import router
from modin_tpu.plan.ir import (
    Filter,
    GroupbyAgg,
    Map,
    PlanNode,
    Project,
    Reduce,
    Scan,
    Sort,
    Source,
    walk,
)

#: the sort-shaped host-kernel families the kernel router arbitrates
SORT_SHAPED = frozenset({"median", "quantile", "nunique", "mode"})

#: measured walls below this never trigger a wall_divergence re-plan —
#: at single-millisecond scale the "divergence" is scheduler noise
REPLAN_NOISE_FLOOR_S = 0.005

#: correction ratios are clamped here so one pathological measurement
#: cannot push every later crossover to literal infinity.  The bound is
#: deliberately generous: an adversarially-wrong calibration table can be
#: off by six orders of magnitude (claimed nanoseconds, measured seconds),
#: and the correction must still be able to flip the affected crossovers
MAX_CORRECTION = 1e6

#: fallback coefficients when neither calibration, substrate peaks, nor
#: PERF_HISTORY priors cover a node family (conservative CPU-substrate
#: figures; any measured source immediately supersedes them)
DEFAULT_PRIORS: Dict[str, float] = {
    "parse_bytes_per_s": 120e6,
    "mem_bytes_per_s": 2e9,
    "bytes_per_row": 64.0,
}

OPT_ON: bool = True

_alloc_count = 0
_tls = threading.local()

_priors_lock = named_lock("plan.optimizer")
#: None = not yet resolved; False = no history available; dict = priors.
#: set_priors installs a forced table (tests, the adversarial bench leg).
_priors: Any = None
_priors_forced = False


def opt_alloc_count() -> int:
    """Strategy-set allocations so far: the Off-mode zero-overhead
    assertion (no :class:`PlanStrategies` is ever built while
    ``MODIN_TPU_OPT=Off``)."""
    return _alloc_count


class NodeStrategy:
    """One plan node's chosen strategy legs and cost estimate.

    ``legs`` maps leg name (kernel / layout / compile / residency) to the
    planned choice — an EXPLAIN annotation for every leg, and the consult
    answer for the legs in ``firm``.  Non-firm legs defer to the live
    router (which sees per-column strategies and real row counts the plan
    cannot); re-planning promotes legs to firm as evidence arrives.
    """

    __slots__ = (
        "node",
        "legs",
        "leg_ops",
        "firm",
        "est_rows",
        "est_bytes",
        "est_s",
        "measured_s",
        "measured_bytes",
        "donate",
    )

    def __init__(self, node: PlanNode):
        self.node = node
        self.legs: Dict[str, str] = {}
        self.leg_ops: Dict[str, str] = {}
        self.firm: Set[str] = set()
        self.est_rows: Optional[int] = None
        self.est_bytes: Optional[int] = None
        self.est_s: float = 0.0
        self.measured_s: Optional[float] = None
        self.measured_bytes: Optional[int] = None
        self.donate: bool = True


class PlanStrategies:
    """The joint strategy annotation for one plan materialization."""

    __slots__ = (
        "by_node",
        "replans",
        "fired",
        "correction",
        "root",
        "done",
        "priors",
    )

    def __init__(self) -> None:
        global _alloc_count
        _alloc_count += 1
        self.by_node: Dict[int, NodeStrategy] = {}
        self.replans: List[dict] = []
        self.fired: Set[Tuple[Any, str]] = set()
        self.correction: float = 1.0
        self.root: Optional[PlanNode] = None
        self.done: Optional[dict] = None
        self.priors: Dict[str, float] = dict(DEFAULT_PRIORS)


def _on_opt_mode(param: Any) -> None:
    global OPT_ON
    OPT_ON = param.get().lower() != "off"
    # install/clear the router consult hook with the mode: Off pays one
    # `is not None` check per router decision and nothing else
    router._opt_consult = _consult if OPT_ON else None


def set_priors(priors: Optional[Dict[str, Any]]) -> None:
    """Force the PERF_HISTORY priors (tests, the adversarial bench leg)
    or reset to lazy resolution (None)."""
    global _priors, _priors_forced
    with _priors_lock:
        _priors = priors if priors is not None else None
        _priors_forced = priors is not None


def default_history_path() -> Optional[str]:
    """The repo-root ``PERF_HISTORY.json`` when running from a checkout
    (bench / CI); installed packages have no ledger and return None."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
    path = os.path.join(here, "PERF_HISTORY.json")
    return path if os.path.exists(path) else None


def priors_from_history(path: Optional[str] = None) -> Optional[dict]:
    """Cost-model priors seeded from the PERF_HISTORY ledger.

    Recorded per-op walls become per-row coefficients (the op's own scale
    key selects the row count it was measured at, exactly as the
    regression gate compares them); later runs supersede earlier ones, so
    the model measurably tracks its own workload across rounds.  Derived
    crossover seeds:

    - ``reduce_s_per_row`` / ``sortred_s_per_row`` / ``groupby_s_per_row``
      from the headline ``sum`` / ``median`` / ``gb_sum`` walls;
    - ``sort_s_per_row`` from the graftsort ``gs_*`` family;
    - ``scan_s_per_row`` from the graftstream ``oocore_stream`` wall.

    Returns None when no ledger is readable (the model runs on
    :data:`DEFAULT_PRIORS`).
    """
    from modin_tpu.observability import perf_history as ph

    if path is None:
        path = default_history_path()
    if path is None:
        return None
    try:
        with open(path) as f:
            ledger = json.load(f)
    except (OSError, ValueError):
        return None
    runs = ledger.get("runs") if isinstance(ledger, dict) else None
    if not isinstance(runs, list):
        return None
    s_per_row: Dict[str, float] = {}
    for run in runs:
        if not isinstance(run, dict):
            continue
        scale = run.get("scale")
        scale = scale if isinstance(scale, dict) else {}
        for op, entry in (run.get("ops") or {}).items():
            wall = (entry or {}).get("modin_tpu_s")
            if not isinstance(wall, (int, float)) or wall <= 0:
                continue
            field = ph._op_scale_field(op)
            rows = scale.get(field) if field else None
            if rows is None:
                rows = scale.get("rows", run.get("rows"))
            if isinstance(rows, (int, float)) and rows > 0:
                s_per_row[op] = float(wall) / float(rows)
    if not s_per_row:
        return None
    priors: Dict[str, Any] = dict(DEFAULT_PRIORS)
    priors["s_per_row"] = s_per_row
    for key, candidates in (
        ("reduce_s_per_row", ("sum", "mean")),
        ("sortred_s_per_row", ("median", "nunique", "mode1")),
        ("groupby_s_per_row", ("gb_sum", "gb_mean", "groupby_sum")),
        ("sort_s_per_row", ("gs_median", "gs_sort", "sort_values")),
        ("scan_s_per_row", ("oocore_stream", "oocore_serial")),
    ):
        for op in candidates:
            if op in s_per_row:
                priors[key] = s_per_row[op]
                break
    priors["source"] = path
    return priors


def _resolve_priors() -> Dict[str, Any]:
    global _priors
    with _priors_lock:
        if _priors is not None:
            return _priors if _priors is not False else dict(DEFAULT_PRIORS)
        resolved = priors_from_history()
        _priors = resolved if resolved is not None else False
        return _priors if _priors is not False else dict(DEFAULT_PRIORS)


# ---------------------------------------------------------------------- #
# the plan-time cost model
# ---------------------------------------------------------------------- #


def _scan_bytes(node: Scan) -> Optional[int]:
    """Sniffed source size of one scan (the same figure graftstream's
    residency consult uses), or None when the source is not stat-able."""
    try:
        path = node.read_kwargs.get("filepath_or_buffer")
        if path is None:
            return None
        return int(node.dispatcher.file_size(path))
    except Exception:  # an unsniffable source simply gets no size-based estimate; planning must never fail a query
        return None


def _source_shape(node: Source) -> Tuple[Optional[int], Optional[int]]:
    """(rows, bytes) of an already-materialized Source frame, forcing
    nothing (a deferred/planned source estimates as unknown)."""
    try:
        frame = node.qc._modin_frame
        if frame is None:
            return None, None
        from modin_tpu.streaming import windows as stream_windows

        return len(frame), int(stream_windows.frame_nbytes(frame))
    except Exception:  # shape sniffing is best-effort; unknown shapes fall back to priors
        return None, None


def estimate_selectivity(mask: PlanNode) -> float:
    """Estimated fraction of rows a filter mask passes.

    Seeded from the comparison operator's shape (equality selects far
    fewer rows than an order comparison; conjunctions multiply,
    disjunctions saturate) — the histogram fast-path statistics refine
    these at the kernel layer, but at plan time the operator is the
    signal that is always available.
    """
    if isinstance(mask, Map):
        method = str(mask.method).lower().strip("_")
        if method in ("eq",):
            return 0.1
        if method in ("ne",):
            return 0.9
        if method in ("gt", "lt", "ge", "le"):
            return 0.5
        if method in ("isin", "isna", "isnull"):
            return 0.2
        if method in ("notna", "notnull"):
            return 0.8
        if method in ("and", "mul"):
            sels = [estimate_selectivity(c) for c in mask.children]
            out = 1.0
            for s in sels:
                out *= s
            return max(out, 0.01)
        if method in ("or", "add"):
            return min(
                sum(estimate_selectivity(c) for c in mask.children), 1.0
            )
        if method in ("invert", "not"):
            return 1.0 - estimate_selectivity(mask.children[0])
    return 0.8


def _estimate_nodes(
    root: PlanNode,
    priors: Dict[str, Any],
    correction: float,
    table: Optional[Dict[str, float]],
) -> Dict[int, dict]:
    """Bottom-up (rows, bytes, seconds) estimate per node id.

    Seconds are subtree-cumulative, matching the instrumented lowering's
    ``total_s`` semantics so the divergence comparison is like-for-like.
    The ``correction`` multiplier carries re-plan evidence: measured
    walls that overshot the model scale every later estimate.
    """
    peaks = None
    try:
        from modin_tpu.observability import costs as graftcost

        peaks = graftcost.substrate_peaks()
    except Exception:  # no peaks means the priors' fallback bandwidth; planning must never fail a query
        peaks = None
    mem_bw = float(
        (peaks or {}).get("bytes_per_s") or priors["mem_bytes_per_s"]
    )
    parse_bw = float(priors.get("parse_bytes_per_s") or 120e6)
    bytes_per_row = float(priors.get("bytes_per_row") or 64.0)
    s_per_row = priors.get("s_per_row") or {}

    est: Dict[int, dict] = {}
    for node in walk(root):
        child = est.get(id(node.children[0])) if node.children else None
        rows = child["rows"] if child else None
        nbytes = child["bytes"] if child else None
        child_s = sum(est[id(c)]["s"] for c in node.children if id(c) in est)
        own_s = 0.0
        if isinstance(node, Scan):
            nbytes = _scan_bytes(node)
            if nbytes is not None:
                rows = max(int(nbytes / bytes_per_row), 1)
                scan_coeff = priors.get("scan_s_per_row")
                own_s = (
                    rows * float(scan_coeff)
                    if scan_coeff
                    else nbytes / parse_bw
                )
                if node.pruned is not None and len(node.all_columns):
                    frac = max(len(node.pruned), 1) / len(node.all_columns)
                    nbytes = int(nbytes * frac)
                    if node.pushed:
                        own_s *= frac
        elif isinstance(node, Source):
            rows, nbytes = _source_shape(node)
        elif isinstance(node, Filter):
            sel = estimate_selectivity(node.children[1])
            if rows is not None:
                rows = max(int(rows * sel), 1)
            if nbytes is not None:
                own_s = nbytes / mem_bw
                nbytes = max(int(nbytes * sel), 1)
        elif isinstance(node, Project):
            if nbytes is not None:
                width = None
                if isinstance(node.children[0], Scan):
                    width = len(node.children[0].all_columns) or None
                frac = (
                    len(node.keys) / width
                    if width
                    else 0.5
                )
                nbytes = max(int(nbytes * min(frac, 1.0)), 1)
                own_s = nbytes / mem_bw
        elif isinstance(node, Map):
            if nbytes is not None:
                own_s = nbytes / mem_bw
        elif isinstance(node, Reduce):
            own_s = _reduce_cost(
                node, rows, nbytes, table, priors, mem_bw, s_per_row
            )
            rows, nbytes = 1, 8
        elif isinstance(node, GroupbyAgg):
            coeff = priors.get("groupby_s_per_row")
            if coeff and rows is not None:
                own_s = rows * float(coeff)
            elif nbytes is not None:
                own_s = 2.0 * nbytes / mem_bw
            if rows is not None:
                rows = max(int(rows**0.5), 1)
                nbytes = rows * 16
        elif isinstance(node, Sort):
            coeff = priors.get("sort_s_per_row")
            if table is not None and rows is not None:
                own_s = table["device_sort_s"] * calstore.nlogn_scale(
                    rows, int(table["rows"])
                )
            elif coeff and rows is not None:
                own_s = rows * float(coeff)
            elif nbytes is not None and rows is not None:
                own_s = nbytes * max(rows, 2).bit_length() / mem_bw
        est[id(node)] = {
            "rows": rows,
            "bytes": nbytes,
            "s": own_s * correction + child_s,
        }
    return est


def _reduce_cost(
    node: Reduce,
    rows: Optional[int],
    nbytes: Optional[int],
    table: Optional[Dict[str, float]],
    priors: Dict[str, Any],
    mem_bw: float,
    s_per_row: Dict[str, float],
) -> float:
    """One reduction's own estimated seconds (the cheaper of the kernel
    router's predicted sides when the family is sort-shaped and a
    calibration table is resolved)."""
    if node.method in SORT_SHAPED:
        if table is not None and rows is not None:
            try:
                costs = router.predicted_costs(
                    node.method, rows, ["sort"], table
                )
                return min(costs["device_s"], costs["host_s"])
            except KeyError:
                pass
        coeff = priors.get("sortred_s_per_row")
        if coeff and rows is not None:
            return rows * float(coeff)
    coeff = priors.get("reduce_s_per_row")
    if coeff and rows is not None:
        return rows * float(coeff)
    return (nbytes / mem_bw) if nbytes is not None else 0.0


def plan_cost(root: PlanNode) -> float:
    """Total modeled cost of a plan (seconds): the rewrite engine's
    cost-gate objective.  Uses only already-resolved calibration (never
    triggers measurement) so rule evaluation stays microseconds."""
    priors = _resolve_priors()
    est = _estimate_nodes(root, priors, 1.0, router.calibration_peek())
    entry = est.get(id(root))
    return float(entry["s"]) if entry else 0.0


# ---------------------------------------------------------------------- #
# choose(): the joint plan-time pass
# ---------------------------------------------------------------------- #


def choose(
    root: PlanNode,
    state: Optional[PlanStrategies] = None,
    exclude: Optional[Set[int]] = None,
) -> PlanStrategies:
    """Annotate every plan node with its jointly-chosen strategy legs.

    One pass per materialization: estimates flow bottom-up, then each
    strategy-bearing node gets its legs under the joint constraints
    (windowed ⇒ staged compile ⇒ no donation).  With ``state`` given the
    pass is a RE-plan: existing annotations are updated in place for the
    nodes not in ``exclude`` (the already-lowered memo), carrying the
    accumulated correction factor into every refreshed estimate.
    """
    replanning = state is not None
    if state is None:
        state = PlanStrategies()
        state.root = root
        state.priors = _resolve_priors()
    exclude = exclude or set()
    with graftscope.span(
        "opt.choose",
        layer="QUERY-COMPILER",
        replanning=replanning,
        correction=round(state.correction, 3),
    ):
        table = router.calibration_peek()
        est = _estimate_nodes(root, state.priors, state.correction, table)
        for node in walk(root):
            if id(node) in exclude:
                continue
            st = state.by_node.get(id(node))
            if st is None:
                st = NodeStrategy(node)
                state.by_node[id(node)] = st
            entry = est.get(id(node), {})
            st.est_rows = entry.get("rows")
            st.est_bytes = entry.get("bytes")
            st.est_s = float(entry.get("s") or 0.0)
            # strategy legs are chosen over the node's INPUT shape (the
            # rows/bytes the kernel actually consumes): a reduction's own
            # output is one row, which decides nothing
            child_entry = (
                est.get(id(node.children[0]), {}) if node.children else {}
            )
            _choose_node(node, st, state, table, child_entry)
    emit_metric("opt.choose", 1)
    return state


def _choose_node(
    node: PlanNode,
    st: NodeStrategy,
    state: PlanStrategies,
    table: Optional[Dict[str, float]],
    child_entry: Dict[str, Any],
) -> None:
    """One node's strategy legs under the joint constraints."""
    in_rows = child_entry.get("rows")
    in_bytes = child_entry.get("bytes")
    if isinstance(node, (Reduce, GroupbyAgg)):
        groupby = isinstance(node, GroupbyAgg)
        residency = _plan_residency(in_bytes)
        st.legs["residency"] = residency
        st.leg_ops["residency"] = (
            "scan_groupby" if groupby else "scan_reduce"
        )
        st.firm.add("residency")
        if residency == "windowed":
            # joint constraints: a windowed tail replays the segment per
            # window — a whole-plan compile never amortizes, and donating
            # the inputs would free buffers the window loop still owns
            st.legs["compile"] = "staged"
            st.firm.add("compile")
            st.donate = False
        else:
            st.legs["compile"] = (
                "fused" if _would_fuse(in_rows) else "staged"
            )
        if not groupby and node.method in SORT_SHAPED:
            st.legs["kernel"] = _plan_kernel(node, in_rows, state, table)
            st.leg_ops["kernel"] = node.method
    elif isinstance(node, Sort):
        st.legs["layout"] = _plan_layout(in_rows, table)
        st.leg_ops["layout"] = "sort"


def _plan_residency(in_bytes: Optional[int]) -> str:
    """Mirror of ``decide_residency``'s Auto arm over the plan-time
    estimate of the consumed working set (same ledger, same headroom
    arithmetic), so steady-state plans agree with the live router and
    only re-plans deviate."""
    from modin_tpu.config import StreamMode
    from modin_tpu.core.memory import device_ledger

    mode = StreamMode.get().lower()
    if mode == "resident":
        return "resident"
    if mode == "windowed":
        return "windowed"
    budget = device_ledger.budget()
    if budget is None or in_bytes is None:
        return "resident"
    headroom = budget - max(device_ledger.total_bytes(), 0)
    return "windowed" if in_bytes > headroom else "resident"


def _would_fuse(est_rows: Optional[int]) -> bool:
    from modin_tpu.config import FuseMinRows, FuseMode

    mode = FuseMode.get().lower()
    if mode == "fused":
        return True
    if mode == "staged":
        return False
    return est_rows is not None and est_rows >= int(FuseMinRows.get())


def _plan_kernel(
    node: Reduce,
    in_rows: Optional[int],
    state: PlanStrategies,
    table: Optional[Dict[str, float]],
) -> str:
    """Annotated device/host leg for a sort-shaped reduction.

    A live whole-result graftview artifact answers for free: the ``view``
    leg.  Otherwise the kernel router's own predicted costs (under the
    current correction) pick the side.  The annotation firms up only
    after a re-plan — pre-divergence the runtime ``decide()`` sees the
    real per-column strategies and stays authoritative.
    """
    if _view_hit(node):
        return "view"
    if table is None or in_rows is None:
        return "device"
    try:
        costs = router.predicted_costs(node.method, in_rows, ["sort"], table)
    except KeyError:
        return "device"
    device_s = costs["device_s"] * state.correction
    if device_s - costs["host_s"] > router.MIN_SAVINGS_S:
        return "host"
    return "device"


def _view_hit(node: Reduce) -> bool:
    """Whether a live graftview artifact already answers this reduction
    over an in-memory Source (planning probe: no metrics, no LRU touch)."""
    child = node.children[0]
    if not isinstance(child, Source):
        return False
    try:
        from modin_tpu.views import registry as view_registry

        frame = child.qc._modin_frame
        if frame is None:
            return False
        sortred = f"sortred.{node.method}"
        for col in frame._columns:
            for kind in view_registry.column_artifact_kinds(col):
                if kind == "reduce" or kind == sortred:
                    return True
        return False
    except Exception:  # the view probe is advisory; a failed peek just loses the free-leg annotation
        return False


def _plan_layout(
    in_rows: Optional[int], table: Optional[Dict[str, float]]
) -> str:
    """Annotated local/sharded leg (EXPLAIN only; the live
    ``decide_layout`` stays authoritative — it sees payload widths)."""
    if (
        table is None
        or "device_shuffle_s" not in table
        or in_rows is None
    ):
        return "local"
    logscale = calstore.nlogn_scale(in_rows, int(table["rows"]))
    sharded_s = table["device_shuffle_s"] * logscale
    local_s = table["device_sort_s"] * logscale
    return "sharded" if sharded_s < local_s else "local"


# ---------------------------------------------------------------------- #
# lowering integration: node scope, observation, re-planning
# ---------------------------------------------------------------------- #


def begin(state: PlanStrategies, root: PlanNode, memo: dict) -> None:
    """Install a strategy set for one lowering pass (called by
    ``lowering.lower_traced``; always paired with :func:`end`)."""
    state.root = root
    state.done = memo
    _tls.state = state
    _tls.stack = []


def end() -> None:
    _tls.state = None
    _tls.stack = None


def push_node(node: PlanNode) -> None:
    state = getattr(_tls, "state", None)
    if state is not None:
        _tls.stack.append(state.by_node.get(id(node)))


def pop_node() -> None:
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


def _current() -> Tuple[Optional[PlanStrategies], Optional[NodeStrategy]]:
    state = getattr(_tls, "state", None)
    if state is None:
        return None, None
    stack = getattr(_tls, "stack", None)
    return state, (stack[-1] if stack else None)


def donate_ok() -> bool:
    """Whether the current node's plan admits input donation (graftfuse
    consults this before building donate_cols): False once the joint
    constraints or a re-plan marked the plan memory-pressured."""
    _state, st = _current()
    return st.donate if st is not None else True


def note_stream_bytes(nbytes: int) -> None:
    """graftstream reports the sniffed working set of a streamed source
    (EXPLAIN renders it against the estimate)."""
    _state, st = _current()
    if st is not None:
        st.measured_bytes = int(nbytes)


def observe(node: PlanNode, total_s: float) -> None:
    """Feed one lowered node's measured wall back into the model; fires
    the ``wall_divergence`` re-plan when the estimate was wrong by more
    than ``MODIN_TPU_OPT_REPLAN_FACTOR``."""
    state = getattr(_tls, "state", None)
    if state is None:
        return
    st = state.by_node.get(id(node))
    if st is None:
        return
    st.measured_s = total_s
    if st.est_s <= 0.0 or total_s <= REPLAN_NOISE_FLOOR_S:
        return
    from modin_tpu.config import OptReplanFactor

    factor = float(OptReplanFactor.get())
    if total_s <= st.est_s * factor:
        return
    ratio = min(total_s / st.est_s, MAX_CORRECTION)
    _replan(
        state,
        "wall_divergence",
        key=id(node),
        node_label=type(node).__name__,
        est_s=st.est_s,
        measured_s=total_s,
        correction=ratio,
    )


def _replan(state: PlanStrategies, trigger: str, key: Any, **attrs: Any) -> bool:
    """Re-optimize the not-yet-lowered plan segment; at most once per
    (key, trigger).  Returns whether the re-plan ran."""
    fired_key = (key, trigger)
    if fired_key in state.fired or state.root is None:
        return False
    state.fired.add(fired_key)
    correction = attrs.get("correction")
    if correction is not None:
        state.correction = max(state.correction, float(correction))
    exclude = set(state.done or ())
    t0 = time.perf_counter()
    choose(state.root, state=state, exclude=exclude)
    if trigger == "compile_storm":
        # the storm is a property of the signature, not the estimates: a
        # re-chosen tail would still say "fused" — pin the remaining
        # compile legs staged outright
        for nid, st in state.by_node.items():
            if nid not in exclude and "compile" in st.legs:
                st.legs["compile"] = "staged"
                st.firm.add("compile")
    event = {
        "trigger": trigger,
        "remaining_nodes": len(state.by_node) - len(exclude),
        **{
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in attrs.items()
        },
    }
    state.replans.append(event)
    emit_metric(f"opt.replan.{trigger}", 1)
    if graftscope.TRACE_ON:
        graftscope.finish_span(
            graftscope.start_span(
                "opt.replan",
                layer="QUERY-COMPILER",
                attrs={
                    **event,
                    "replan_s": round(time.perf_counter() - t0, 6),
                },
            )
        )
    return True


# ---------------------------------------------------------------------- #
# the router consult hook
# ---------------------------------------------------------------------- #


def _consult(
    leg: str, choice: str, reason: str, **ctx: Any
) -> Optional[Tuple[str, str]]:
    """Answer one live router decision from the plan-time strategy.

    Returns a replacement ``(choice, reason)`` only where the plan (or a
    re-plan) genuinely disagrees with the live verdict — agreement keeps
    the router's own choice and reason, so steady-state traces are
    indistinguishable from the pre-graftopt engine.
    """
    state, st = _current()
    if state is None:
        return None
    if leg == "residency":
        return _consult_residency(state, st, choice, ctx)
    if leg == "compile":
        return _consult_compile(state, st, choice, ctx)
    if leg == "kernel":
        return _consult_kernel(state, st, choice, ctx)
    # layout: both calibrated sides scale by the same correction, so a
    # re-plan never flips it — the live decide_layout stays authoritative
    return None


def _consult_residency(
    state: PlanStrategies,
    st: Optional[NodeStrategy],
    choice: str,
    ctx: Dict[str, Any],
) -> Optional[Tuple[str, str]]:
    if st is None or st.leg_ops.get("residency") != ctx.get("op"):
        return None
    planned = st.legs.get("residency")
    if planned is None:
        return None
    if planned == "resident" and choice == "windowed":
        # live ledger pressure contradicts the plan: flip the remaining
        # segment (the re-choose reads the pressured ledger and windows
        # the tail), follow the live verdict for THIS node
        st.legs["residency"] = "windowed"
        st.legs["compile"] = "staged"
        st.firm.update(("residency", "compile"))
        st.donate = False
        _replan(
            state,
            "ledger_pressure",
            key=id(st.node),
            est_bytes=int(ctx.get("est_bytes") or 0),
        )
        return ("windowed", "graftopt_replan")
    if planned != choice:
        return (planned, "graftopt")
    return None


def _consult_compile(
    state: PlanStrategies,
    st: Optional[NodeStrategy],
    choice: str,
    ctx: Dict[str, Any],
) -> Optional[Tuple[str, str]]:
    if choice == "fused":
        level = 0
        try:
            from modin_tpu.plan import fuse

            level = fuse.storm_level(ctx.get("sig"))
        except Exception:  # storm bookkeeping is advisory; an unreadable level keeps the live verdict
            level = 0
        if level >= 1:
            if st is not None:
                st.legs["compile"] = "staged"
                st.firm.add("compile")
            _replan(
                state,
                "compile_storm",
                key=("sig", ctx.get("sig")),
                storm_level=level,
            )
            return ("staged", "graftopt_replan")
    if st is not None and "compile" in st.firm:
        planned = st.legs.get("compile")
        if planned is not None and planned != choice:
            return (planned, "graftopt")
    return None


def _consult_kernel(
    state: PlanStrategies,
    st: Optional[NodeStrategy],
    choice: str,
    ctx: Dict[str, Any],
) -> Optional[Tuple[str, str]]:
    if state.correction <= 1.0:
        # pre-divergence the live decide() is authoritative: it sees the
        # real per-column strategies the plan could only guess at
        return None
    table = router.calibration_peek()
    if table is None:
        return None
    try:
        costs = router.predicted_costs(
            str(ctx.get("op")),
            int(ctx.get("n") or 0),
            list(ctx.get("strategies") or ["sort"]),
            table,
        )
    except KeyError:
        return None
    corrected = (
        "host"
        if costs["device_s"] * state.correction - costs["host_s"]
        > router.MIN_SAVINGS_S
        else "device"
    )
    if corrected != choice:
        if st is not None:
            st.legs["kernel"] = corrected
            st.firm.add("kernel")
        return (corrected, "graftopt_replan")
    return None


# the subscription fires immediately (installing/clearing the router hook
# for the current mode), so it lives below every function it references
from modin_tpu.config import OptMode as _OptMode  # noqa: E402

_OptMode.subscribe(_on_opt_mode)
