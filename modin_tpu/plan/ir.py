"""graftplan logical-plan IR: immutable operator nodes over a shared DAG.

Nodes are cheap metadata shells — no node ever touches device data or reads
a file.  Children are held by reference, so a subtree shared between two
consumers (the classic case: the filter mask's predicate branch and the main
spine both hanging off one scan) is ONE node, and lowering computes it once.
Rewrites (:mod:`modin_tpu.plan.rules`) never mutate nodes in place; they
rebuild the spine with :func:`transform`, which memoizes by identity so
sharing survives every rewrite pass.

Schema answers (``columns``, ``known_dtypes``) are derived lazily from the
leaves so a deferred compiler can answer metadata questions without forcing
the plan; anything the IR cannot answer exactly (e.g. scan dtypes, which
need a full parse) returns ``None`` and the caller materializes instead —
a wrong metadata answer is never an option.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np
import pandas

#: Longest plan chain the deferral guards will build before materializing
#: (the planner's analogue of ``ops/lazy.py``'s ``_MAX_NODES`` window):
#: keeps rewrite/lowering recursion bounded and plan rewrites cheap.
MAX_PLAN_DEPTH = 160


#: Sentinel for "this argument position is the i-th plan child" inside a
#: :class:`Map` node's argument template.
class Ref:
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __repr__(self) -> str:
        return f"Ref({self.index})"


class PlanNode:
    """Base class: one logical operator; ``children`` are data-flow inputs.

    ``depth`` is the longest root-to-leaf path, maintained at construction:
    the deferral guards decline to extend a plan past
    :data:`MAX_PLAN_DEPTH` (materializing instead, exactly like
    ``ops/lazy.py``'s ``_MAX_NODES`` overflow), which also bounds every
    recursive walk (transform / structural_key / lowering / explain) well
    inside Python's recursion limit.
    """

    kind = "node"
    __slots__ = ("children", "depth")

    def __init__(self, children: Tuple["PlanNode", ...] = ()):
        self.children = tuple(children)
        self.depth = 1 + max((c.depth for c in self.children), default=0)

    # -- schema ---------------------------------------------------------- #

    @property
    def columns(self) -> pandas.Index:
        """Output column labels (exact, derived from the leaves)."""
        raise NotImplementedError

    def known_dtypes(self) -> Optional[pandas.Series]:
        """Exact output dtypes, or None when only a full parse could know."""
        return None

    def row_key(self) -> Any:
        """Row-lineage token: two nodes with equal row keys are guaranteed
        positionally aligned (same source rows in the same order)."""
        return self.children[0].row_key()

    # -- structure ------------------------------------------------------- #

    def with_children(self, children: Tuple["PlanNode", ...]) -> "PlanNode":
        """Rebuild this node over new children, preserving the payload."""
        raise NotImplementedError

    def payload_key(self) -> Any:
        """Hashable payload identity (children excluded) for CSE."""
        return ()

    def label(self) -> str:
        """One-line description for EXPLAIN rendering."""
        return self.kind


class Scan(PlanNode):
    """A deferred file read: dispatcher + original kwargs + column metadata.

    ``all_columns`` is the post-``usecols`` column set learned by the cheap
    header sniff at defer time; ``pruned`` (set by the pushdown rule) is the
    subset that actually needs parsing, kept in file order.  ``colarg`` names
    the reader kwarg that carries the projection ("usecols" for the text
    family, "columns" for parquet-shaped dispatchers).
    """

    kind = "scan"
    __slots__ = (
        "dispatcher", "read_kwargs", "all_columns", "pruned", "colarg",
        "pushed", "origin", "cache",
    )

    def __init__(
        self,
        dispatcher: type,
        read_kwargs: dict,
        all_columns: pandas.Index,
        pruned: Optional[Tuple] = None,
        colarg: str = "usecols",
        pushed: bool = False,
        origin: Optional["Scan"] = None,
    ):
        super().__init__(())
        self.dispatcher = dispatcher
        self.read_kwargs = read_kwargs
        self.all_columns = all_columns
        self.pruned = tuple(pruned) if pruned is not None else None
        self.colarg = colarg
        self.pushed = pushed
        # rewrites produce fresh (pruned) Scan objects per materialization;
        # ``origin`` anchors them to the node the user's pending plans hold,
        # and ``cache`` (on the origin) memoizes lowered reads so a source
        # shared by several plans/materializations parses once per
        # projection, never once per force()
        self.origin = origin if origin is not None else self
        self.cache = {} if origin is None else None

    @property
    def columns(self) -> pandas.Index:
        if self.pruned is None:
            return self.all_columns
        keep = set(self.pruned)
        return pandas.Index([c for c in self.all_columns if c in keep])

    def row_key(self) -> Any:
        return ("scan", id(self))

    def with_children(self, children) -> "Scan":
        return self

    def label(self) -> str:
        path = self.read_kwargs.get("filepath_or_buffer") or self.read_kwargs.get(
            "path", "?"
        )
        cols = (
            f"{len(self.pruned)}/{len(self.all_columns)} cols (pruned"
            + (f", {self.colarg} pushed into reader)" if self.pushed else ")")
            if self.pruned is not None
            else f"{len(self.all_columns)} cols"
        )
        return f"scan[{self.dispatcher.__name__}] {path} [{cols}]"


class Source(PlanNode):
    """A leaf wrapping an already-materialized eager query compiler."""

    kind = "source"
    __slots__ = ("qc",)

    def __init__(self, qc: Any):
        super().__init__(())
        self.qc = qc

    @property
    def columns(self) -> pandas.Index:
        return self.qc.get_columns()

    def known_dtypes(self) -> Optional[pandas.Series]:
        return self.qc.dtypes

    def row_key(self) -> Any:
        return ("source", id(self.qc))

    def with_children(self, children) -> "Source":
        return self

    def label(self) -> str:
        return f"source[{len(self.columns)} cols]"


class Project(PlanNode):
    """Column selection/reordering: ``child[labels]`` (or positions)."""

    kind = "project"
    __slots__ = ("keys", "numeric", "out_hint")

    def __init__(
        self,
        child: PlanNode,
        keys: Tuple,
        numeric: bool = False,
        out_hint: Optional[str] = None,
    ):
        super().__init__((child,))
        self.keys = tuple(keys)
        self.numeric = numeric
        self.out_hint = out_hint

    @property
    def columns(self) -> pandas.Index:
        if self.numeric:
            return self.children[0].columns[list(self.keys)]
        return pandas.Index(list(self.keys))

    def known_dtypes(self) -> Optional[pandas.Series]:
        child = self.children[0].known_dtypes()
        if child is None:
            return None
        if self.numeric:
            return child.iloc[list(self.keys)]
        return child.loc[list(self.keys)]

    def with_children(self, children) -> "Project":
        return Project(children[0], self.keys, self.numeric, self.out_hint)

    def payload_key(self) -> Any:
        return (self.keys, self.numeric, self.out_hint)

    def label(self) -> str:
        keys = list(self.keys)
        shown = keys if len(keys) <= 6 else keys[:6] + ["..."]
        return f"project{shown}"


class Filter(PlanNode):
    """Row selection by a boolean-mask subplan: ``child[mask]``.

    ``children == (child, mask)``; the mask is a full plan subtree (usually
    sharing the child's scan — the diamond CSE generalizes).
    """

    kind = "filter"
    __slots__ = ()

    def __init__(self, child: PlanNode, mask: PlanNode):
        super().__init__((child, mask))

    @property
    def columns(self) -> pandas.Index:
        return self.children[0].columns

    def known_dtypes(self) -> Optional[pandas.Series]:
        return self.children[0].known_dtypes()

    def row_key(self) -> Any:
        return ("filter", id(self))

    def with_children(self, children) -> "Filter":
        return Filter(children[0], children[1])

    def label(self) -> str:
        return "filter"


class Map(PlanNode):
    """A length-preserving elementwise op: one query-compiler method call.

    ``method`` is the eager QC method to invoke at lowering (``gt``, ``add``,
    ``unary_math``, ``abs``, ...); ``args``/``kwargs`` are the call template,
    with :class:`Ref` placeholders standing for lowered plan children.
    ``children[0]`` is the receiver; further children are operand subplans.
    """

    kind = "map"
    __slots__ = ("method", "args", "kwargs", "out_columns", "bool_out", "out_hint")

    def __init__(
        self,
        children: Tuple[PlanNode, ...],
        method: str,
        args: Tuple = (),
        kwargs: Optional[dict] = None,
        out_columns: Optional[pandas.Index] = None,
        bool_out: bool = False,
        out_hint: Optional[str] = None,
    ):
        super().__init__(children)
        self.method = method
        self.args = tuple(args)
        self.kwargs = dict(kwargs or {})
        self.out_columns = (
            out_columns if out_columns is not None else children[0].columns
        )
        self.bool_out = bool_out
        self.out_hint = out_hint

    @property
    def columns(self) -> pandas.Index:
        return self.out_columns

    def known_dtypes(self) -> Optional[pandas.Series]:
        if self.bool_out:
            return pandas.Series(
                [np.dtype(bool)] * len(self.out_columns), index=self.out_columns
            )
        return None

    def with_children(self, children) -> "Map":
        return Map(
            children,
            self.method,
            self.args,
            self.kwargs,
            self.out_columns,
            self.bool_out,
            self.out_hint,
        )

    def payload_key(self) -> Any:
        def arg_key(a):
            if isinstance(a, Ref):
                return ("ref", a.index)
            return (type(a).__name__, repr(a))

        return (
            self.method,
            tuple(arg_key(a) for a in self.args),
            tuple(sorted((k, repr(v)) for k, v in self.kwargs.items())),
            tuple(self.out_columns),
            self.bool_out,
            self.out_hint,
        )

    def label(self) -> str:
        shown = [
            f"${a.index}" if isinstance(a, Ref) else repr(a) for a in self.args
        ]
        return f"map:{self.method}({', '.join(shown)})"


class Reduce(PlanNode):
    """An axis reduction — a materialization point in the deferred mode.

    ``fused`` is set by the map→reduce fusion rule: the maps below stay
    deferred ``LazyExpr`` columns and the reduction consumes them through
    ``run_fused``'s tail mechanism, one XLA program for the whole chain.
    """

    kind = "reduce"
    __slots__ = ("method", "call_kwargs", "fused", "fused_maps")

    def __init__(
        self,
        child: PlanNode,
        method: str,
        call_kwargs: dict,
        fused: bool = False,
        fused_maps: int = 0,
    ):
        super().__init__((child,))
        self.method = method
        self.call_kwargs = dict(call_kwargs)
        self.fused = fused
        self.fused_maps = fused_maps

    @property
    def columns(self) -> pandas.Index:
        # reductions collapse the axis; the lowered eager result carries the
        # real labels, which depend on dtype selection we don't predict here
        return self.children[0].columns

    def with_children(self, children) -> "Reduce":
        return Reduce(
            children[0], self.method, self.call_kwargs, self.fused, self.fused_maps
        )

    def payload_key(self) -> Any:
        return (
            self.method,
            tuple(sorted((k, repr(v)) for k, v in self.call_kwargs.items())),
            self.fused,
        )

    def label(self) -> str:
        tag = f" (fused over {self.fused_maps} maps)" if self.fused else ""
        return f"reduce:{self.method}{tag}"


class GroupbyAgg(PlanNode):
    """A groupby aggregation — also a materialization point.

    ``by`` is either a label list or a :class:`Ref` into ``children`` when
    the grouper is itself a deferred subplan.
    """

    kind = "groupby_agg"
    __slots__ = ("by", "agg_func", "call_kwargs")

    def __init__(
        self,
        children: Tuple[PlanNode, ...],
        by: Any,
        agg_func: Any,
        call_kwargs: dict,
    ):
        super().__init__(children)
        self.by = by
        self.agg_func = agg_func
        self.call_kwargs = dict(call_kwargs)

    @property
    def columns(self) -> pandas.Index:
        return self.children[0].columns

    def with_children(self, children) -> "GroupbyAgg":
        return GroupbyAgg(children, self.by, self.agg_func, self.call_kwargs)

    def payload_key(self) -> Any:
        return (
            repr(self.by),
            repr(self.agg_func),
            tuple(sorted((k, repr(v)) for k, v in self.call_kwargs.items())),
        )

    def label(self) -> str:
        by = f"${self.by.index}" if isinstance(self.by, Ref) else self.by
        return f"groupby_agg[by={by}, agg={self.agg_func}]"


class Sort(PlanNode):
    """Row reordering by column values (deferred; changes row lineage)."""

    kind = "sort"
    __slots__ = ("sort_columns", "ascending", "call_kwargs")

    def __init__(
        self, child: PlanNode, sort_columns: Any, ascending: Any, call_kwargs: dict
    ):
        super().__init__((child,))
        self.sort_columns = sort_columns
        self.ascending = ascending
        self.call_kwargs = dict(call_kwargs)

    @property
    def columns(self) -> pandas.Index:
        return self.children[0].columns

    def known_dtypes(self) -> Optional[pandas.Series]:
        return self.children[0].known_dtypes()

    def row_key(self) -> Any:
        return ("sort", id(self))

    def with_children(self, children) -> "Sort":
        return Sort(children[0], self.sort_columns, self.ascending, self.call_kwargs)

    def payload_key(self) -> Any:
        return (
            repr(self.sort_columns),
            repr(self.ascending),
            tuple(sorted((k, repr(v)) for k, v in self.call_kwargs.items())),
        )

    def label(self) -> str:
        return f"sort[{self.sort_columns}]"


# ---------------------------------------------------------------------- #
# DAG utilities
# ---------------------------------------------------------------------- #


def walk(root: PlanNode):
    """Yield every distinct node once, children before parents (postorder)."""
    seen = set()
    stack = [(root, False)]
    while stack:
        node, expanded = stack.pop()
        if id(node) in seen:
            continue
        if expanded:
            seen.add(id(node))
            yield node
        else:
            stack.append((node, True))
            for child in node.children:
                if id(child) not in seen:
                    stack.append((child, False))


def count_nodes(root: PlanNode) -> int:
    return sum(1 for _ in walk(root))


def transform(root: PlanNode, fn) -> Tuple[PlanNode, int]:
    """Rebuild the DAG bottom-up through ``fn``, preserving sharing.

    ``fn(node) -> PlanNode | None`` is called on each node AFTER its children
    have been rebuilt; None keeps the node.  Returns (new_root, change_count).
    Identity-memoized: a shared subtree is visited and rebuilt exactly once,
    so diamonds stay diamonds.
    """
    memo: dict = {}
    changes = 0

    def rebuild(node: PlanNode) -> PlanNode:
        nonlocal changes
        hit = memo.get(id(node))
        if hit is not None:
            return hit
        new_children = tuple(rebuild(c) for c in node.children)
        rebuilt = (
            node
            if all(a is b for a, b in zip(new_children, node.children))
            else node.with_children(new_children)
        )
        replaced = fn(rebuilt)
        if replaced is not None and replaced is not rebuilt:
            changes += 1
            rebuilt = replaced
        memo[id(node)] = rebuilt
        return rebuilt

    return rebuild(root), changes


def structural_key(root: PlanNode, memo: Optional[dict] = None) -> Any:
    """Structural identity of a subtree (leaves keyed by object identity).

    Two subtrees with equal keys compute the same values over the same
    source rows — the CSE merge criterion.
    """
    if memo is None:
        memo = {}
    # the memo holds (node, key) — keeping the node alive — because a bare
    # id->key map is an id-reuse hazard: a dropped intermediate node's id
    # can be recycled by a brand-new node mid-rewrite and inherit the stale
    # key (the same guard recovery.py applies to its weakref provenance)
    hit = memo.get(id(root))
    if hit is not None and hit[0] is root:
        return hit[1]
    if root.children:
        tail = tuple(structural_key(c, memo) for c in root.children)
    else:
        tail = ("leaf", id(root))
    key = (root.kind, root.payload_key(), tail)
    memo[id(root)] = (root, key)
    return key
