"""graftplan runtime: the glue between the query compiler and the plan IR.

The TPU query compiler's plan-capable methods carry a one-line guard — "if a
plan is pending, try to defer" — and everything behind that guard lives
here: the mode gate (``MODIN_TPU_PLAN``), the scan sniff that makes a read
deferrable, node builders for each operator family, the materialization
(`optimize` + `lower`) path, and the safety predicates (row-lineage
alignment, pushdown eligibility) that decide when deferring is *exactly*
equivalent to eager execution.  Anything the planner cannot prove equivalent
falls back to eager by returning ``None`` — the caller's next line touches
``_modin_frame`` and the pending plan materializes through the property.

Mode semantics:

- ``Off``   — nothing ever defers; today's eager behavior, bit for bit.
- ``Auto``  — supported reads defer; chained plan-capable calls extend the
  plan; any other operation (or metadata the IR cannot answer exactly)
  materializes through the existing seams.
- ``Force`` — Auto, plus plan-capable calls on *already-materialized* TPU
  compilers re-enter planning through a :class:`~modin_tpu.plan.ir.Source`
  leaf, so rewrite rules keep applying after a materialization point.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import pandas

from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import spans as graftscope
from modin_tpu.plan import lowering
from modin_tpu.plan.ir import (
    MAX_PLAN_DEPTH,
    Filter,
    GroupbyAgg,
    Map,
    PlanNode,
    Project,
    Reduce,
    Ref,
    Scan,
    Sort,
    Source,
)
from modin_tpu.plan.rules import optimize
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL

#: Module-level fast path, graftscope-style: the per-op guard in the query
#: compiler checks ``self._plan is not None or runtime.FORCE_ON`` — while the
#: mode is not Force, an eager compiler pays one attribute read per call.
FORCE_ON: bool = False


def plan_mode() -> str:
    from modin_tpu.config import PlanMode

    return PlanMode.get()


def _on_plan_param(_param: Any = None) -> None:
    global FORCE_ON
    try:
        FORCE_ON = plan_mode() == "Force"
    except ImportError:  # config not importable during teardown
        FORCE_ON = False


def _install_subscription() -> None:
    from modin_tpu.config import PlanMode

    PlanMode.subscribe(_on_plan_param)


# ---------------------------------------------------------------------- #
# Scan deferral
# ---------------------------------------------------------------------- #

#: read_csv kwargs stripped for the header sniff (they either conflict with
#: ``nrows=0`` or only affect the body).
_SNIFF_DROP = ("filepath_or_buffer", "iterator", "chunksize", "nrows", "skipfooter")


def _requests_extension_dtype(dtype: Any) -> bool:
    """Whether a ``dtype=`` read kwarg asks for any pandas extension dtype.

    Extension results (NA-backed Int64/boolean/...) violate the IR's
    "comparisons are plain bool" dtype claims, so such reads stay eager.
    """
    no_default = pandas.api.extensions.no_default
    if dtype is None or dtype is no_default:
        return False
    values = dtype.values() if isinstance(dtype, dict) else [dtype]
    for value in values:
        try:
            if isinstance(
                pandas.api.types.pandas_dtype(value),
                pandas.api.extensions.ExtensionDtype,
            ):
                return True
        except TypeError:
            return True  # unparseable request: assume the worst, stay eager
    return False


def defer_read(dispatcher: type, kwargs: dict) -> Optional[Any]:
    """Defer a text-family read into a Scan-rooted plan, or None for eager.

    The sniff parses ONLY the header (``nrows=0``) to learn the post-
    ``usecols`` column labels — exact metadata for a few KB of IO.  Any
    sniff failure (missing file, bad kwargs, malformed header) declines the
    deferral so the eager path raises at the call site with today's timing.
    """
    try:
        mode = plan_mode()
    except ImportError:
        return None
    if mode == "Off":
        return None
    kwargs = dispatcher.normalize_read_kwargs(dict(kwargs))
    if kwargs.get("iterator") or kwargs.get("chunksize") is not None:
        return None  # these return parser iterators, not frames
    path = kwargs.get("filepath_or_buffer")
    if not dispatcher.is_local_plain_file(path):
        return None
    dtype_backend = kwargs.get("dtype_backend")
    if dtype_backend is not None and dtype_backend is not (
        pandas.api.extensions.no_default
    ):
        # extension-backed frames break the IR's "comparisons are plain
        # bool" dtype guarantees — stay eager
        return None
    if _requests_extension_dtype(kwargs.get("dtype")):
        return None  # same guarantee: dtype={'a': 'Int64'} etc. stays eager
    sniff_kwargs = {k: v for k, v in kwargs.items() if k not in _SNIFF_DROP}
    try:
        header = dispatcher.read_fn(path, nrows=0, **sniff_kwargs)
        columns = pandas.Index(header.columns)
    except Exception:
        # any sniff failure means "not deferrable"; the eager read then
        # raises the same error at the same call site
        return None
    scan = Scan(dispatcher, dict(kwargs), columns, colarg="usecols")
    emit_metric("plan.defer.scan", 1)
    return dispatcher.query_compiler_cls.from_plan(scan)


#: read_csv kwargs that make a reader-level projection unsafe to push,
#: mapped to the values meaning "feature disabled": the parse of a
#: surviving column (or the frame's index) could depend on a pruned one.
#: NOTE ``index_col`` has NO harmless falsy value — 0 means "first column
#: is the index", and pandas resolves positional index_col *within* the
#: usecols subset, so any set index_col blocks the pushdown.
_PUSHDOWN_BLOCKERS = (
    ("index_col", (None,)),
    ("converters", (None,)),
    ("skipfooter", (None, 0)),
    ("parse_dates", (None, False)),
)


def scan_supports_pushdown(scan: Scan) -> bool:
    """Whether narrowing this scan's reader projection is exactly safe."""
    if scan.colarg != "usecols":
        return False
    kwargs = scan.read_kwargs
    no_default = pandas.api.extensions.no_default
    for key, disabled in _PUSHDOWN_BLOCKERS:
        value = kwargs.get(key)
        if value is no_default or any(value is d for d in disabled):
            continue
        return False
    usecols = kwargs.get("usecols")
    if usecols is not None and usecols is not no_default and callable(usecols):
        return False
    dtype = kwargs.get("dtype")
    if isinstance(dtype, dict) and any(
        k not in set(scan.all_columns) for k in dtype
    ):
        # pandas accepts positional (int) dtype keys, resolved against the
        # full column set; the pushed projection filters this dict by LABEL,
        # so a non-label key would silently change the surviving columns'
        # parse — keep the full-width read instead
        return False
    names = kwargs.get("names")
    if names is not None and names is not no_default:
        return False
    # the pushed projection is label-based: every sniffed label must be a
    # plain unique string (a MultiIndex header yields tuple labels, which
    # pandas' usecols rejects outright)
    return scan.all_columns.is_unique and all(
        isinstance(c, str) for c in scan.all_columns
    )


# ---------------------------------------------------------------------- #
# Node builders (the per-op deferral guards call these)
# ---------------------------------------------------------------------- #


def _plan_of(qc: Any) -> Optional[PlanNode]:
    """The operand's plan — wrapping eager compilers in Source under Force.

    While a lowering pass is running on this thread, eager compilers stay
    eager: lowering replays plan nodes through the same guarded methods, and
    re-entering planning there would recurse forever.  A plan at the depth
    cap also declines (the caller's eager body then materializes it) — the
    planner's analogue of ``ops/lazy.py``'s ``_MAX_NODES`` overflow, keeping
    pathological op loops from building unbounded (and unboundedly
    recursive) plan chains.
    """
    plan = qc._plan
    if plan is not None and plan.depth >= MAX_PLAN_DEPTH:
        return None
    if plan is None and FORCE_ON and not lowering.in_lowering():
        plan = _source_of(qc)
    return plan


def _source_of(qc: Any) -> Source:
    """One memoized Source leaf per eager compiler (keyed on its frame).

    Force-mode guards must hand every consumer of one compiler the SAME
    leaf: row keys are identity-based, so a fresh Source per guard call
    would never match between a frame and its mask/operand and filters and
    series-series binaries would silently stay eager.  The memo drops
    itself when the compiler's frame is rebound (e.g. a reduction adopting
    its lowered input)."""
    source = getattr(qc, "_plan_source", None)
    if source is None or source.qc._frame is not qc._frame:
        source = Source(qc.eager_snapshot())
        qc._plan_source = source
    return source


def _stamp_hint(qc: Any, plan: PlanNode) -> None:
    """Late-bind the pandas layer's shape hint into the operand's node.

    The API layer tags a compiler as a Series (``_shape_hint = "column"``)
    *after* the deferring call returns, so the hint is only knowable once
    the node is consumed by the next operator; lowering needs it on the
    intermediate eager compilers for the series/frame binary label rules.
    """
    if isinstance(plan, (Project, Map)) and plan.out_hint is None and (
        qc._shape_hint is not None
    ):
        plan.out_hint = qc._shape_hint


def defer_project(qc: Any, key: Any, numeric: bool) -> Optional[Any]:
    plan = _plan_of(qc)
    if plan is None:
        return None
    keys = list(key)
    if numeric:
        try:
            keys = [int(k) for k in keys]
        except (TypeError, ValueError):
            return None
        width = len(plan.columns)
        if any(k < -width or k >= width for k in keys):
            return None  # out of range: eager raises at the call site
    else:
        columns = plan.columns
        if not columns.is_unique or any(k not in columns for k in keys):
            return None
    _stamp_hint(qc, plan)
    return type(qc).from_plan(Project(plan, tuple(keys), numeric))


def defer_filter(qc: Any, mask_qc: Any) -> Optional[Any]:
    """Defer ``df[bool_series]`` when the mask is a provably aligned,
    provably boolean subplan of the same row lineage."""
    plan = _plan_of(qc)
    if plan is None or mask_qc._plan is None:
        return None
    mask_plan = mask_qc._plan
    if mask_plan.depth >= MAX_PLAN_DEPTH:
        return None
    mask_dtypes = mask_plan.known_dtypes()
    if (
        mask_dtypes is None
        or len(mask_dtypes) != 1
        or mask_dtypes.iloc[0] != bool
        or plan.row_key() != mask_plan.row_key()
    ):
        return None
    _stamp_hint(qc, plan)
    _stamp_hint(mask_qc, mask_plan)
    return type(qc).from_plan(Filter(plan, mask_plan))


_SCALAR_OPERANDS = (int, float, bool, str, type(None))


def _known_bool(plan: PlanNode) -> bool:
    dtypes = plan.known_dtypes()
    return dtypes is not None and all(dt == bool for dt in dtypes)


def _known_plain(plan: PlanNode) -> bool:
    """No KNOWN extension dtype in the node's output.

    Scans are gated to plain numpy dtypes at defer time (dtype_backend and
    extension ``dtype=`` requests decline deferral), so unknown dtypes are
    plain; a Source over an extension-backed frame reports them exactly.
    """
    dtypes = plan.known_dtypes()
    return dtypes is None or not any(
        isinstance(dt, pandas.api.extensions.ExtensionDtype) for dt in dtypes
    )


def defer_binary(qc: Any, op: str, other: Any, kwargs: dict) -> Optional[Any]:
    import numpy as np

    plan = _plan_of(qc)
    if plan is None:
        return None
    cls = type(qc)
    # comparisons yield plain bool for plain-dtype operands; extension
    # operands (possible under Force over e.g. Int64 frames) and string
    # comparisons may produce NA-backed boolean extension results, and
    # logical ops on non-bool ints are bitwise — none of those claim bool
    bool_out = (
        op in cls._CMP_OPS and not isinstance(other, str) and _known_plain(plan)
    ) or (op in cls._LOGICAL_OPS and _known_bool(plan))
    hint = qc._shape_hint
    if isinstance(other, _SCALAR_OPERANDS + (np.generic,)) and not isinstance(
        other, bytes
    ):
        _stamp_hint(qc, plan)
        node = Map(
            (plan,),
            op,
            (other,),
            kwargs,
            out_columns=plan.columns,
            bool_out=bool_out,
            out_hint=hint,
        )
        return cls.from_plan(node, hint)
    if isinstance(other, cls) and other._plan is not None:
        other_plan = other._plan
        if other_plan.depth >= MAX_PLAN_DEPTH:
            return None
        if plan.row_key() != other_plan.row_key():
            return None
        if op in cls._LOGICAL_OPS:
            bool_out = bool_out and _known_bool(other_plan)
        elif op in cls._CMP_OPS:
            bool_out = bool_out and _known_plain(other_plan)
        other_hint = other._shape_hint
        if hint == "column" and other_hint == "column":
            a, b = plan.columns[0], other_plan.columns[0]
            label = a if a == b else MODIN_UNNAMED_SERIES_LABEL
            out_columns = pandas.Index([label])
        elif hint is None and other_hint is None:
            if not plan.columns.equals(other_plan.columns):
                return None
            out_columns = plan.columns
        else:
            return None
        _stamp_hint(qc, plan)
        _stamp_hint(other, other_plan)
        node = Map(
            (plan, other_plan),
            op,
            (Ref(1),),
            kwargs,
            out_columns=out_columns,
            bool_out=bool_out,
            out_hint=hint,
        )
        return cls.from_plan(node, hint)
    return None


#: Unary QC methods that defer as single-child maps (all length-preserving,
#: columns unchanged); value is whether the result is provably boolean.
UNARY_MAP_METHODS = {
    "abs": False,
    "negative": False,
    "invert": False,
    "isna": True,
    "notna": True,
}


def defer_unary(
    qc: Any, method: str, args: Tuple = (), kwargs: Optional[dict] = None,
    bool_out: bool = False,
) -> Optional[Any]:
    plan = _plan_of(qc)
    if plan is None:
        return None
    if not all(isinstance(a, _SCALAR_OPERANDS) for a in args):
        return None
    _stamp_hint(qc, plan)
    hint = qc._shape_hint
    node = Map(
        (plan,),
        method,
        tuple(args),
        dict(kwargs or {}),
        out_columns=plan.columns,
        bool_out=bool_out,
        out_hint=hint,
    )
    return type(qc).from_plan(node, hint)


def defer_sort(
    qc: Any, columns: Any, ascending: Any, kwargs: dict
) -> Optional[Any]:
    plan = _plan_of(qc)
    if plan is None:
        return None
    col_list = [columns] if not isinstance(columns, (list, tuple)) else list(columns)
    plan_columns = plan.columns
    if not plan_columns.is_unique or any(c not in plan_columns for c in col_list):
        return None
    _stamp_hint(qc, plan)
    node = Sort(plan, columns, ascending, kwargs)
    return type(qc).from_plan(node, qc._shape_hint)


# ---------------------------------------------------------------------- #
# Materialization points
# ---------------------------------------------------------------------- #


def _optimize_and_lower(
    qc: Any, root: PlanNode, instrument: Optional[dict] = None
) -> Tuple[Any, dict]:
    """One optimize+lower pass; records EXPLAIN attribution on ``qc``."""
    from modin_tpu.plan import optimizer
    from modin_tpu.plan.ir import count_nodes

    cost_model = optimizer.plan_cost if optimizer.OPT_ON else None
    with graftscope.span(
        "plan.optimize", layer="QUERY-COMPILER", nodes=count_nodes(root)
    ):
        optimized, applied = optimize(root, cost_model=cost_model)
    passes = (applied[-1][1] + 1) if applied else 1
    emit_metric("plan.optimize.passes", passes)
    for name, _pass_index in applied:
        emit_metric(f"plan.rule.{name}", 1)
    strategies = optimizer.choose(optimized) if optimizer.OPT_ON else None
    result, memo = lowering.lower_traced(
        optimized, instrument=instrument, strategies=strategies
    )
    qc._plan_explain = (root, optimized, applied)
    qc._plan_strategies = strategies
    return result, memo


def explain_analyze(qc: Any) -> Optional[Tuple[Any, dict, Any]]:
    """EXPLAIN ANALYZE: execute ``qc``'s plan with per-node instrumentation.

    Returns ``(stats, instrument, (root, optimized, applied))`` — the
    :class:`~modin_tpu.observability.meters.QueryStats` rollup, the node-id
    -> measured-actuals dict, and the plan history of this run (the
    actuals key off ``id()`` of nodes in the returned ``optimized`` tree)
    — or None when there is nothing to analyze (a plain eager compiler
    with no plan history).

    A *pending* plan is executed and its frame adopted, exactly like
    :func:`force` (so a later op on the compiler continues from the
    materialized result, and the analyze run IS the query's execution — the
    bit-exactness contract).  An already-materialized compiler with plan
    history re-executes the recorded plan (scans may be served from the
    scan cache; the annotations say so via their measured bytes/time) and
    the re-run result is discarded.
    """
    from modin_tpu.observability import meters as graftmeter

    # tolerate non-graftplan compilers the way the analyze=False branch
    # does: report "nothing to analyze" instead of AttributeError
    plan = getattr(qc, "_plan", None)
    pending = plan is not None
    if pending:
        root = plan
    else:
        history = getattr(qc, "_plan_explain", None)
        if history is None:
            return None
        root = history[0]
    instrument: dict = {}
    with graftmeter.query_stats("explain.analyze") as stats:
        result, _memo = _optimize_and_lower(qc, root, instrument=instrument)
    if pending:
        qc._frame = result._modin_frame
        qc._plan = None
    return stats, instrument, qc._plan_explain


def force(qc: Any):
    """Materialize a pending plan; returns the concrete TpuDataframe."""
    plan = qc._plan
    if plan is None:
        if qc._frame is None:
            raise RuntimeError(
                "deferred query compiler used after free(): its plan was "
                "dropped and no frame was ever materialized"
            )
        return qc._frame
    result, _memo = _optimize_and_lower(qc, plan)
    qc._frame = result._modin_frame
    qc._plan = None
    return qc._frame


def _adopt_lowered_input(qc: Any, memo: dict) -> None:
    """Adopt the materialization's lowered INPUT frame back into ``qc`` so a
    later op on the same compiler reuses the scan instead of re-reading.
    Only fires while ``qc`` still holds a real pending plan (a Force-mode
    eager compiler has none) — the optimized root's first child is the
    reduction/groupby input by construction."""
    lowered_input = memo.get(id(qc._plan_explain[1].children[0]))
    if lowered_input is not None and qc._plan is not None:
        qc._frame = lowered_input._modin_frame
        qc._plan = None


def run_reduce(qc: Any, op: str, call_kwargs: dict) -> Optional[Any]:
    """Reductions are materialization points: append the Reduce node, run
    the whole optimized plan, and adopt the reduction INPUT back into ``qc``
    so a later op on the same compiler reuses the scan instead of re-reading.
    """
    plan = _plan_of(qc)
    if plan is None:
        return None
    _stamp_hint(qc, plan)
    root = Reduce(plan, op, call_kwargs)
    result, memo = _optimize_and_lower(qc, root)
    _adopt_lowered_input(qc, memo)
    return result


def run_groupby_agg(
    qc: Any, by: Any, agg_func: Any, call_kwargs: dict
) -> Optional[Any]:
    """Groupby aggregations materialize like reductions (their output index
    is group-dependent, which the IR does not model)."""
    plan = _plan_of(qc)
    if plan is None:
        return None
    cls = type(qc)
    children: Tuple[PlanNode, ...] = (plan,)
    by_payload = by
    if isinstance(by, cls):
        if by._plan is None or by._plan.row_key() != plan.row_key():
            return None
        _stamp_hint(by, by._plan)
        children = (plan, by._plan)
        by_payload = Ref(1)
    elif not (
        isinstance(by, (str, list, tuple))
        and (isinstance(by, str) or all(isinstance(b, str) for b in by))
    ):
        return None
    _stamp_hint(qc, plan)
    root = GroupbyAgg(children, by_payload, agg_func, call_kwargs)
    result, memo = _optimize_and_lower(qc, root)
    _adopt_lowered_input(qc, memo)
    return result


# ---------------------------------------------------------------------- #
# Metadata service & public helpers
# ---------------------------------------------------------------------- #


def plan_columns(qc: Any) -> pandas.Index:
    return qc._plan.columns


def plan_dtypes(qc: Any) -> Optional[pandas.Series]:
    return qc._plan.known_dtypes()


def defer_frame(obj: Any) -> Any:
    """Public opt-in: root a plan at an existing TPU DataFrame/Series/QC.

    Returns the same API-level type wrapped over a Source-rooted deferred
    compiler; chained plan-capable calls then extend the plan even under
    ``MODIN_TPU_PLAN=Auto``.
    """
    qc = getattr(obj, "_query_compiler", obj)
    planned = type(qc).from_plan(Source(qc.eager_snapshot()), qc._shape_hint)
    if hasattr(obj, "_query_compiler"):
        return type(obj)(query_compiler=planned)
    return planned


_install_subscription()
_on_plan_param()
