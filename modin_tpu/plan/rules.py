"""graftplan rewrite rules: pure ``Plan -> Plan | None`` functions.

Each rule takes a plan root and returns a rewritten root, or ``None`` when it
has nothing to do.  The engine (:func:`optimize`) applies the catalog to
fixpoint under a bounded pass budget (``MODIN_TPU_PLAN_MAX_PASSES``) — a rule
that keeps "improving" forever cannot wedge a query.  Rules never mutate
nodes; rebuilding goes through :func:`modin_tpu.plan.ir.transform`, which
preserves DAG sharing (a diamond stays one node).

Catalog (in application order):

1. ``pushdown-filter``      — ``Filter(Project(x))`` / ``Filter(Map(x))``
   commute to ``Project(Filter(x))`` / ``Map(Filter(x))``: filters migrate
   toward the scan so every operator above them touches fewer rows.  Valid
   because projects/maps are row-preserving and the mask is an independent
   subtree (it never reads its consumer's output).
2. ``cse``                  — merges structurally identical subtrees into one
   shared node (the whole-tree generalization of ``_linearize``'s diamond
   sharing); downstream, lowering computes each merged node once.
3. ``prune-columns``        — reverse-topological required-column analysis:
   each scan learns exactly which of its columns any consumer (including
   filter predicates reached through mask subtrees) will ever read.
4. ``pushdown-project-into-scan`` — converts the pruning annotation into the
   reader's own projection argument (``usecols`` for the text family), so
   dropped columns are never parsed, not merely never uploaded.
5. ``fuse-map-reduce``      — tags a reduce whose input is a map chain as
   fused: lowering keeps the maps as deferred ``LazyExpr`` columns and the
   reduction consumes them through ``run_fused``'s tail, one XLA program.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from modin_tpu.logging.metrics import emit_metric
from modin_tpu.plan.ir import (
    Filter,
    Map,
    PlanNode,
    Project,
    Reduce,
    Scan,
    Sort,
    structural_key,
    transform,
    walk,
)

#: Marker for "every column of this node is required".
ALL = object()


def push_filter_down(root: PlanNode) -> Optional[PlanNode]:
    """Commute filters below projects and maps (toward the scan)."""

    def fn(node: PlanNode) -> Optional[PlanNode]:
        if not isinstance(node, Filter):
            return None
        child, mask = node.children
        if isinstance(child, Project):
            return Project(
                Filter(child.children[0], mask),
                child.keys,
                child.numeric,
                child.out_hint,
            )
        if isinstance(child, Map) and len(child.children) == 1:
            # single-input maps commute trivially; multi-input maps would
            # need the filter replicated into every operand branch, which
            # multiplies gathers instead of saving them — leave those be
            return child.with_children((Filter(child.children[0], mask),))
        return None

    new_root, changes = transform(root, fn)
    return new_root if changes else None


def common_subexpression_elimination(root: PlanNode) -> Optional[PlanNode]:
    """Merge structurally identical subtrees into one shared node."""
    canonical: Dict[Any, PlanNode] = {}
    keys: dict = {}

    def fn(node: PlanNode) -> Optional[PlanNode]:
        key = structural_key(node, keys)
        seen = canonical.get(key)
        if seen is not None and seen is not node:
            return seen
        canonical[key] = node
        return None

    new_root, changes = transform(root, fn)
    return new_root if changes else None


def _required_columns(root: PlanNode) -> Dict[int, Any]:
    """Per-node required output columns: a set of labels, or ALL.

    Reverse-topological walk (parents before children); a node consumed by
    several parents gets the union of their demands.  The root's own output
    is observable, so it always requires ALL.
    """
    order = list(walk(root))  # children before parents
    order.reverse()
    req: Dict[int, Any] = {id(root): ALL}

    def add(node: PlanNode, needed: Any) -> None:
        cur = req.get(id(node))
        if cur is ALL or needed is ALL:
            req[id(node)] = ALL
        elif cur is None:
            req[id(node)] = set(needed)
        else:
            cur.update(needed)

    for node in order:
        needed = req.get(id(node), set())
        if isinstance(node, Project):
            if node.numeric:
                add(node.children[0], ALL)
            else:
                add(node.children[0], set(node.keys))
        elif isinstance(node, Filter):
            child, mask = node.children
            add(child, needed)
            add(mask, ALL)
        elif isinstance(node, Sort):
            keys = node.sort_columns
            keys = [keys] if not isinstance(keys, (list, tuple)) else list(keys)
            if needed is ALL:
                add(node.children[0], ALL)
            else:
                add(node.children[0], set(needed) | set(keys))
        else:
            # map / reduce / groupby_agg (and anything future): conservatively
            # demand every column of every input
            for child in node.children:
                add(child, ALL)
    return req


def prune_dead_columns(root: PlanNode) -> Optional[PlanNode]:
    """Annotate each scan with the columns its consumers actually read."""
    req = _required_columns(root)

    def fn(node: PlanNode) -> Optional[PlanNode]:
        if not isinstance(node, Scan) or node.pruned is not None:
            return None
        needed = req.get(id(node), ALL)
        if needed is ALL:
            return None
        keep = tuple(c for c in node.all_columns if c in needed)
        if len(keep) >= len(node.all_columns):
            return None
        return Scan(
            node.dispatcher, node.read_kwargs, node.all_columns, keep,
            node.colarg, origin=node.origin,
        )

    # NOTE: req was computed against the ORIGINAL node identities; transform
    # rebuilds bottom-up, but scans are leaves, so their identity at fn-time
    # is unchanged and the lookup stays valid.
    new_root, changes = transform(root, fn)
    return new_root if changes else None


def pushdown_projection_into_scan(root: PlanNode) -> Optional[PlanNode]:
    """Make the pruning annotation real: narrow the reader's projection.

    This rule is a no-op for scans whose kwargs the pushdown gate rejects
    (callable usecols, index_col, converters, ...) — those keep full-width
    parses and the plan above them still prunes post-parse.  The gate lives
    in :func:`modin_tpu.plan.runtime.scan_supports_pushdown` so the deferral
    and pushdown decisions share one source of truth.
    """
    from modin_tpu.plan.runtime import scan_supports_pushdown

    def fn(node: PlanNode) -> Optional[PlanNode]:
        if (
            isinstance(node, Scan)
            and node.pruned is not None
            and not node.pushed
            and scan_supports_pushdown(node)
        ):
            return Scan(
                node.dispatcher,
                node.read_kwargs,
                node.all_columns,
                node.pruned,
                node.colarg,
                pushed=True,
                origin=node.origin,
            )
        return None

    new_root, changes = transform(root, fn)
    return new_root if changes else None


def fuse_map_reduce(root: PlanNode) -> Optional[PlanNode]:
    """Tag reduces fed by map chains: the chain lowers as ONE fused program.

    Mechanically the fusion is carried out by ``ops/lazy.py`` — lowering a
    map produces deferred ``LazyExpr`` columns, and the eager reduction
    consumes their ``raw`` forms through ``run_fused``'s tail — so the rule's
    job is to assert the boundary in the IR (and in EXPLAIN output), counting
    how many map nodes ride into the reduction's program.
    """

    def fn(node: PlanNode) -> Optional[PlanNode]:
        if not isinstance(node, Reduce) or node.fused:
            return None
        chain = 0
        cursor = node.children[0]
        while isinstance(cursor, Map):
            chain += 1
            cursor = cursor.children[0]
        if chain == 0:
            return None
        return Reduce(node.children[0], node.method, node.call_kwargs, True, chain)

    new_root, changes = transform(root, fn)
    return new_root if changes else None


#: The ordered rule catalog: (name, rule).
RULES: Tuple[Tuple[str, Any], ...] = (
    ("pushdown-filter", push_filter_down),
    ("cse", common_subexpression_elimination),
    ("prune-columns", prune_dead_columns),
    ("pushdown-project-into-scan", pushdown_projection_into_scan),
    ("fuse-map-reduce", fuse_map_reduce),
)


#: Cost-gate tolerance: a rewrite is rejected only when the modeled cost
#: RISES by more than this factor.  Generous on purpose — the catalog's
#: rules are all structurally profitable (pushdown / CSE / pruning reduce
#: bytes or work by construction) and the plan-time model is coarse; the
#: gate exists to stop a future rule (or a miscalibrated model) from
#: pessimizing a plan, not to second-guess clear wins.
COST_GATE_TOLERANCE = 1.05

#: The rules whose relative ORDER the cost model may rearrange within a
#: pass: filter pushdown and CSE both reshape the same spine, and which
#: one should see the plan first depends on estimated selectivity (a
#: near-no-op filter is better merged than pushed).  The rest of the
#: catalog keeps its fixed position — ordering is only sound between
#: rules that commute on every plan, which these two do (both are
#: applied to fixpoint anyway; the order decides which shape the OTHER
#: one gets to see first each pass).
_COST_ORDERED = frozenset({"pushdown-filter", "cse"})


def _cost_ordered(
    root: PlanNode, rules: Tuple[Tuple[str, Any], ...], cost_model: Any
) -> List[Tuple[str, Any]]:
    """The rule catalog for one pass, with the ``_COST_ORDERED`` block
    sorted by modeled benefit (descending) on the current plan."""
    block = [(name, rule) for name, rule in rules if name in _COST_ORDERED]
    if len(block) < 2:
        return list(rules)
    base = cost_model(root)
    benefit: dict = {}
    for name, rule in block:
        try:
            candidate = rule(root)
        except Exception:  # benefit probing must not mask the real application's error path below
            candidate = None
        benefit[name] = base - cost_model(candidate) if candidate is not None else 0.0
    block.sort(key=lambda item: benefit[item[0]], reverse=True)
    ordered: List[Tuple[str, Any]] = []
    block_iter = iter(block)
    for name, rule in rules:
        ordered.append(next(block_iter) if name in _COST_ORDERED else (name, rule))
    return ordered


def optimize(
    root: PlanNode,
    max_passes: Optional[int] = None,
    cost_model: Any = None,
) -> Tuple[PlanNode, List[Tuple[str, int]]]:
    """Apply the rule catalog to fixpoint under the pass budget.

    Returns ``(optimized_root, applied)`` where ``applied`` lists
    ``(rule_name, pass_index)`` in application order — the per-rule
    attribution EXPLAIN renders.

    ``cost_model`` (graftopt's ``plan_cost``: plan -> estimated seconds)
    arms cost-gated rewriting: a rule application is kept only while the
    modeled cost does not rise beyond :data:`COST_GATE_TOLERANCE`, and the
    pushdown-filter/CSE pair is re-ordered each pass by modeled benefit.
    None (``MODIN_TPU_OPT=Off``) is byte-identical to the historical
    fixed-order, always-accept behavior.
    """
    if max_passes is None:
        from modin_tpu.config import PlanMaxPasses

        max_passes = PlanMaxPasses.get()
    applied: List[Tuple[str, int]] = []
    for pass_index in range(max(int(max_passes), 1)):
        changed = False
        rules = (
            _cost_ordered(root, RULES, cost_model)
            if cost_model is not None
            else RULES
        )
        for name, rule in rules:
            new_root = rule(root)
            if new_root is not None:
                if cost_model is not None:
                    before = cost_model(root)
                    after = cost_model(new_root)
                    if after > before * COST_GATE_TOLERANCE + 1e-9:
                        emit_metric(f"plan.rule_rejected.{name}", 1)
                        continue
                root = new_root
                applied.append((name, pass_index))
                changed = True
        if not changed:
            break
    return root, applied
