"""graftplan EXPLAIN: human-readable plan rendering with rule attribution.

``df.modin.explain()`` (or ``qc.explain()``) prints the logical plan before
and after the rewrite pass, plus which rules fired on which pass — enough to
debug a plan regression ("why did pushdown stop firing?") from a terminal,
without loading a trace viewer.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from modin_tpu.plan.ir import PlanNode
from modin_tpu.plan.rules import optimize


def render(root: PlanNode) -> str:
    """ASCII tree of a plan; shared (diamond) nodes render once and are
    referenced as ``^N`` afterwards."""
    lines: List[str] = []
    ids: dict = {}

    def visit(node: PlanNode, depth: int) -> None:
        indent = "  " * depth
        seen = ids.get(id(node))
        if seen is not None:
            lines.append(f"{indent}^{seen} (shared {node.kind})")
            return
        ids[id(node)] = len(ids) + 1
        lines.append(f"{indent}#{ids[id(node)]} {node.label()}")
        for child in node.children:
            visit(child, depth + 1)

    visit(root, 0)
    return "\n".join(lines)


def render_attribution(applied: List[Tuple[str, int]]) -> str:
    if not applied:
        return "rewrites: none (plan already optimal)"
    by_rule: dict = {}
    for name, pass_index in applied:
        by_rule.setdefault(name, []).append(pass_index)
    lines = ["rewrites:"]
    for name, passes in by_rule.items():
        shown = ", ".join(str(p) for p in passes)
        lines.append(f"  - {name}: {len(passes)} application(s) (pass {shown})")
    return "\n".join(lines)


def explain_plan(
    root: PlanNode,
    optimized: Optional[PlanNode] = None,
    applied: Optional[List[Tuple[str, int]]] = None,
) -> str:
    if optimized is None:
        optimized, applied = optimize(root)
    parts = [
        "== logical plan (before rewrite) ==",
        render(root),
        "",
        "== logical plan (after rewrite) ==",
        render(optimized),
        "",
        render_attribution(applied or []),
    ]
    return "\n".join(parts)


def explain_qc(qc: Any) -> str:
    """EXPLAIN for a query compiler: pending plan, last-materialized plan,
    or a note that execution is eager."""
    plan = getattr(qc, "_plan", None)
    if plan is not None:
        return "status: deferred (not yet materialized)\n" + explain_plan(plan)
    history = getattr(qc, "_plan_explain", None)
    if history is not None:
        root, optimized, applied = history
        return "status: materialized\n" + explain_plan(root, optimized, applied)
    return (
        "status: eager (no deferred plan; set MODIN_TPU_PLAN=Auto and start "
        "from a deferrable read, or use modin_tpu.plan.defer_frame)"
    )
