"""graftplan EXPLAIN: human-readable plan rendering with rule attribution.

``df.modin.explain()`` (or ``qc.explain()``) prints the logical plan before
and after the rewrite pass, plus which rules fired on which pass — enough to
debug a plan regression ("why did pushdown stop firing?") from a terminal,
without loading a trace viewer.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from modin_tpu.plan.ir import PlanNode
from modin_tpu.plan.rules import optimize


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "?"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


def _fmt_count(n: Optional[float]) -> str:
    """Compact flop/byte-estimate rendering: 1234567 -> ``1.2M``."""
    if n is None:
        return "?"
    n = float(n)
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if n >= scale:
            return f"{n / scale:.1f}{suffix}"
    return f"{n:.0f}"


def _cost_suffix(measured: dict) -> str:
    """The graftcost leg of a node annotation: estimated flops/bytes, the
    padding share of the bytes the node's kernels physically touched, and
    the achieved roofline fraction at the node's measured wall.  Empty when
    cost capture was off for the run."""
    if "est_flops" not in measured:
        return ""
    from modin_tpu.observability import costs as _costs

    est_flops = measured["est_flops"]
    est_bytes = measured["est_bytes"]
    padded = measured.get("padded_bytes", 0)
    waste = measured.get("padding_waste_bytes", 0)
    pad_pct = f"{waste / padded:.0%}" if padded > 0 else "0%"
    roofline = "?"
    try:
        fraction = _costs.roofline_fraction(
            est_flops or None, est_bytes or None, measured["total_s"]
        )
        if fraction is not None:
            roofline = f"{fraction:.1%}"
    except Exception:
        pass
    return (
        f" est_flops={_fmt_count(est_flops)} "
        f"est_bytes={_fmt_bytes(int(est_bytes))} "
        f"padding={pad_pct} roofline={roofline}"
    )


def _actual_suffix(measured: Optional[dict]) -> str:
    """``(actual: ...)`` annotation for one analyzed node."""
    if measured is None:
        return ""
    rows = measured.get("rows")
    return (
        "  (actual: "
        f"time={measured['total_s'] * 1e3:.3f}ms "
        f"self={measured['self_s'] * 1e3:.3f}ms "
        f"rows={'?' if rows is None else rows} "
        f"bytes={_fmt_bytes(measured.get('bytes'))} "
        f"dispatches={measured['dispatches']}"
        f"{_cost_suffix(measured)})"
    )


def _strategy_suffix(st: Any) -> str:
    """graftopt annotation for one node: the chosen strategy legs (``!``
    marks a firm leg the live router will be overridden with), the modeled
    cost, and — once the node lowered — the measured wall beside it."""
    if st is None:
        return ""
    parts = [
        f"{leg}={choice}" + ("!" if leg in st.firm else "")
        for leg, choice in sorted(st.legs.items())
    ]
    if st.est_s > 0.0:
        cost = f"est={st.est_s * 1e3:.3f}ms"
        if st.measured_s is not None:
            cost += f" meas={st.measured_s * 1e3:.3f}ms"
        parts.append(cost)
    if st.measured_bytes is not None:
        parts.append(f"stream_bytes={_fmt_bytes(st.measured_bytes)}")
    if not parts:
        return ""
    return "  [strategy: " + " ".join(parts) + "]"


def render(
    root: PlanNode,
    actuals: Optional[dict] = None,
    strategies: Any = None,
) -> str:
    """ASCII tree of a plan; shared (diamond) nodes render once and are
    referenced as ``^N`` afterwards.  ``actuals`` (EXPLAIN ANALYZE) maps
    ``id(node)`` to its measured entry from the instrumented lowering;
    ``strategies`` (graftopt) annotates each node's chosen execution
    strategy and estimated-vs-measured cost."""
    lines: List[str] = []
    ids: dict = {}
    by_node = strategies.by_node if strategies is not None else {}

    def visit(node: PlanNode, depth: int) -> None:
        indent = "  " * depth
        seen = ids.get(id(node))
        if seen is not None:
            lines.append(f"{indent}^{seen} (shared {node.kind})")
            return
        ids[id(node)] = len(ids) + 1
        suffix = _actual_suffix(actuals.get(id(node))) if actuals else ""
        suffix += _strategy_suffix(by_node.get(id(node)))
        lines.append(f"{indent}#{ids[id(node)]} {node.label()}{suffix}")
        for child in node.children:
            visit(child, depth + 1)

    visit(root, 0)
    return "\n".join(lines)


def render_replans(strategies: Any) -> str:
    """The graftopt re-plan events of one materialization, with trigger
    reason and the evidence that fired each."""
    if strategies is None or not strategies.replans:
        return "re-plans: none"
    lines = [
        f"re-plans: {len(strategies.replans)} "
        f"(correction x{strategies.correction:.2f})"
    ]
    for event in strategies.replans:
        detail = " ".join(
            f"{k}={v}" for k, v in event.items() if k != "trigger"
        )
        lines.append(f"  - {event['trigger']}: {detail}")
    return "\n".join(lines)


def render_attribution(applied: List[Tuple[str, int]]) -> str:
    if not applied:
        return "rewrites: none (plan already optimal)"
    by_rule: dict = {}
    for name, pass_index in applied:
        by_rule.setdefault(name, []).append(pass_index)
    lines = ["rewrites:"]
    for name, passes in by_rule.items():
        shown = ", ".join(str(p) for p in passes)
        lines.append(f"  - {name}: {len(passes)} application(s) (pass {shown})")
    return "\n".join(lines)


def explain_plan(
    root: PlanNode,
    optimized: Optional[PlanNode] = None,
    applied: Optional[List[Tuple[str, int]]] = None,
    strategies: Any = None,
) -> str:
    if optimized is None:
        optimized, applied = optimize(root)
    parts = [
        "== logical plan (before rewrite) ==",
        render(root),
        "",
        "== logical plan (after rewrite) ==",
        render(optimized, strategies=strategies),
        "",
        render_attribution(applied or []),
    ]
    if strategies is not None:
        parts += ["", render_replans(strategies)]
    return "\n".join(parts)


def explain_analyze_qc(qc: Any) -> str:
    """EXPLAIN ANALYZE: run the plan instrumented and render actuals.

    The plan executes for real (a pending plan materializes into the
    compiler, exactly as touching ``_modin_frame`` would — results are
    bit-exact vs plain execution); every executed node is annotated with
    its measured wall time, result rows/bytes, and engine dispatch count,
    and the per-query resource rollup (dispatches, compiles, bytes parsed,
    HBM high-water, spills, recoveries, cache hits) follows the tree.
    """
    from modin_tpu.plan import runtime

    analyzed = runtime.explain_analyze(qc)
    if analyzed is None:
        return (
            "status: eager (nothing to analyze; set MODIN_TPU_PLAN=Auto and "
            "start from a deferrable read, or use modin_tpu.plan.defer_frame)"
        )
    stats, actuals, (root, optimized, applied) = analyzed
    strategies = getattr(qc, "_plan_strategies", None)
    parts = [
        "status: analyzed (plan executed with per-node measurement)",
        "== logical plan (before rewrite) ==",
        render(root),
        "",
        "== logical plan (after rewrite, with actuals) ==",
        render(optimized, actuals=actuals, strategies=strategies),
        "",
        render_attribution(applied or []),
    ]
    if strategies is not None:
        parts += ["", render_replans(strategies)]
    parts += [
        "",
        "== query rollup ==",
        stats.summary(),
    ]
    return "\n".join(parts)


def explain_qc(qc: Any, analyze: bool = False) -> str:
    """EXPLAIN for a query compiler: pending plan, last-materialized plan,
    or a note that execution is eager.  ``analyze=True`` additionally
    executes the plan and annotates every node with measured actuals."""
    if analyze:
        return explain_analyze_qc(qc)
    plan = getattr(qc, "_plan", None)
    if plan is not None:
        return "status: deferred (not yet materialized)\n" + explain_plan(plan)
    history = getattr(qc, "_plan_explain", None)
    if history is not None:
        root, optimized, applied = history
        return "status: materialized\n" + explain_plan(
            root, optimized, applied, getattr(qc, "_plan_strategies", None)
        )
    return (
        "status: eager (no deferred plan; set MODIN_TPU_PLAN=Auto and start "
        "from a deferrable read, or use modin_tpu.plan.defer_frame)"
    )
