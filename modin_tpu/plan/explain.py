"""graftplan EXPLAIN: human-readable plan rendering with rule attribution.

``df.modin.explain()`` (or ``qc.explain()``) prints the logical plan before
and after the rewrite pass, plus which rules fired on which pass — enough to
debug a plan regression ("why did pushdown stop firing?") from a terminal,
without loading a trace viewer.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from modin_tpu.plan.ir import PlanNode
from modin_tpu.plan.rules import optimize


def _fmt_bytes(n: Optional[int]) -> str:
    if n is None:
        return "?"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


def _actual_suffix(measured: Optional[dict]) -> str:
    """``(actual: ...)`` annotation for one analyzed node."""
    if measured is None:
        return ""
    rows = measured.get("rows")
    return (
        "  (actual: "
        f"time={measured['total_s'] * 1e3:.3f}ms "
        f"self={measured['self_s'] * 1e3:.3f}ms "
        f"rows={'?' if rows is None else rows} "
        f"bytes={_fmt_bytes(measured.get('bytes'))} "
        f"dispatches={measured['dispatches']})"
    )


def render(root: PlanNode, actuals: Optional[dict] = None) -> str:
    """ASCII tree of a plan; shared (diamond) nodes render once and are
    referenced as ``^N`` afterwards.  ``actuals`` (EXPLAIN ANALYZE) maps
    ``id(node)`` to its measured entry from the instrumented lowering."""
    lines: List[str] = []
    ids: dict = {}

    def visit(node: PlanNode, depth: int) -> None:
        indent = "  " * depth
        seen = ids.get(id(node))
        if seen is not None:
            lines.append(f"{indent}^{seen} (shared {node.kind})")
            return
        ids[id(node)] = len(ids) + 1
        suffix = _actual_suffix(actuals.get(id(node))) if actuals else ""
        lines.append(f"{indent}#{ids[id(node)]} {node.label()}{suffix}")
        for child in node.children:
            visit(child, depth + 1)

    visit(root, 0)
    return "\n".join(lines)


def render_attribution(applied: List[Tuple[str, int]]) -> str:
    if not applied:
        return "rewrites: none (plan already optimal)"
    by_rule: dict = {}
    for name, pass_index in applied:
        by_rule.setdefault(name, []).append(pass_index)
    lines = ["rewrites:"]
    for name, passes in by_rule.items():
        shown = ", ".join(str(p) for p in passes)
        lines.append(f"  - {name}: {len(passes)} application(s) (pass {shown})")
    return "\n".join(lines)


def explain_plan(
    root: PlanNode,
    optimized: Optional[PlanNode] = None,
    applied: Optional[List[Tuple[str, int]]] = None,
) -> str:
    if optimized is None:
        optimized, applied = optimize(root)
    parts = [
        "== logical plan (before rewrite) ==",
        render(root),
        "",
        "== logical plan (after rewrite) ==",
        render(optimized),
        "",
        render_attribution(applied or []),
    ]
    return "\n".join(parts)


def explain_analyze_qc(qc: Any) -> str:
    """EXPLAIN ANALYZE: run the plan instrumented and render actuals.

    The plan executes for real (a pending plan materializes into the
    compiler, exactly as touching ``_modin_frame`` would — results are
    bit-exact vs plain execution); every executed node is annotated with
    its measured wall time, result rows/bytes, and engine dispatch count,
    and the per-query resource rollup (dispatches, compiles, bytes parsed,
    HBM high-water, spills, recoveries, cache hits) follows the tree.
    """
    from modin_tpu.plan import runtime

    analyzed = runtime.explain_analyze(qc)
    if analyzed is None:
        return (
            "status: eager (nothing to analyze; set MODIN_TPU_PLAN=Auto and "
            "start from a deferrable read, or use modin_tpu.plan.defer_frame)"
        )
    stats, actuals, (root, optimized, applied) = analyzed
    parts = [
        "status: analyzed (plan executed with per-node measurement)",
        "== logical plan (before rewrite) ==",
        render(root),
        "",
        "== logical plan (after rewrite, with actuals) ==",
        render(optimized, actuals=actuals),
        "",
        render_attribution(applied or []),
        "",
        "== query rollup ==",
        stats.summary(),
    ]
    return "\n".join(parts)


def explain_qc(qc: Any, analyze: bool = False) -> str:
    """EXPLAIN for a query compiler: pending plan, last-materialized plan,
    or a note that execution is eager.  ``analyze=True`` additionally
    executes the plan and annotates every node with measured actuals."""
    if analyze:
        return explain_analyze_qc(qc)
    plan = getattr(qc, "_plan", None)
    if plan is not None:
        return "status: deferred (not yet materialized)\n" + explain_plan(plan)
    history = getattr(qc, "_plan_explain", None)
    if history is not None:
        root, optimized, applied = history
        return "status: materialized\n" + explain_plan(root, optimized, applied)
    return (
        "status: eager (no deferred plan; set MODIN_TPU_PLAN=Auto and start "
        "from a deferrable read, or use modin_tpu.plan.defer_frame)"
    )
