"""graftfuse: whole-plan XLA compilation — one donated, bucket-padded
program per query segment.

graftplan's staged lowering replays each plan node through the eager
seams: a ``read_csv(...).query(...)[cols].agg(...)`` pipeline pays one
dispatch for the mask-fused filter compaction (plus a host sync for the
kept-row count) and a second for the trim-fused reduction.  This module
compiles the ENTIRE post-scan segment — the filter/map/project chain AND
its reduce or groupby_agg tail — into ONE jitted XLA program:

- **no compaction**: the filter's keep mask stays a deferred expression
  and the reduction applies it in place (``ops/reductions.reduce_columns_
  masked``); the kept values are the same values a stable compaction would
  have gathered, in the same order, so results match the staged path.
  The logical length rides as a *runtime scalar*, so one executable serves
  every logical length at a physical size.
- **buffer donation**: every input column the device ledger proves has no
  other live consumer (``_DeviceLedger.buffer_consumers == 1``) and that
  can be rebuilt from lineage (exact host copy) is passed in a donated jit
  position — XLA reuses its HBM for the program's intermediates, and the
  column itself becomes *spilled*: the next read restores via lineage
  instead of touching the consumed buffer (the use-after-donate guard).
- **adaptive padding buckets**: fused programs re-specialize per physical
  input size, so a stream of near-miss sizes against one plan signature is
  a recompile storm.  Instead of fixed pow2 steps, the bucket escalates
  from the compile ledger's storm feedback: exact padding until a
  signature proves it storms, then eighth-octave buckets, then pow2
  (:func:`quantize_padded`), applied to the scan's uploads through
  ``ops/structural.pad_bucket_scope``.
- **routing**: ``ops/router.decide_compile`` keeps tiny frames on the
  staged path (trace+compile cost beats one saved dispatch);
  ``MODIN_TPU_FUSE`` pins Auto/Staged/Fused.

The fused program dispatches through ``run_fused`` -> ``JaxWrapper.deploy``
like every other device computation, so resilience retry/rebind, graftcost
capture, and graftmeter accounting see it unchanged; plain ``jnp`` bodies
SPMD-partition over the graftmesh substrate exactly as the staged kernels
do, and the fused cache key carries the mesh shape + device epoch so a
reshape or re-seat never reuses a stale executable.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import pandas

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import spans as graftscope
from modin_tpu.observability.compile_ledger import (
    compiles_on_this_thread,
    get_compile_ledger,
)
from modin_tpu.plan.ir import (
    Filter,
    GroupbyAgg,
    Map,
    PlanNode,
    Project,
    Reduce,
    Ref,
    Scan,
    Source,
    walk,
)
from modin_tpu.serving import context as serving_context

#: mirrored from ``MODIN_TPU_FUSE`` (Staged -> False): ONE module-attr read
#: on the lowering hot path when fusion is pinned off
FUSE_ON: bool = True

#: reductions the masked whole-plan tail expresses exactly (median needs a
#: data-dependent selection; nunique/mode are the sort-shaped family)
SUPPORTED_REDUCE = frozenset(
    {
        "sum", "prod", "mean", "min", "max", "count", "var", "std", "sem",
        "skew", "kurt", "any", "all",
    }
)

#: Map methods the masked walk may replay: the deferral layer only builds
#: Map nodes from these (defer_binary's op table + defer_unary's catalog),
#: and each stays a deferred LazyExpr on device frames
_SUPPORTED_MAP_METHODS = frozenset(
    {
        "add", "radd", "sub", "rsub", "mul", "rmul", "truediv", "rtruediv",
        "floordiv", "rfloordiv", "mod", "rmod", "pow", "rpow",
        "eq", "ne", "lt", "le", "gt", "ge",
        "__and__", "__or__", "__xor__", "__rand__", "__ror__", "__rxor__",
        "abs", "negative", "invert", "isna", "notna",
    }
)


class _Decline(Exception):
    """This segment cannot fuse; the staged lowering proceeds."""


# ---------------------------------------------------------------------- #
# mode flag
# ---------------------------------------------------------------------- #


def _on_fuse_mode(param: Any) -> None:
    global FUSE_ON
    FUSE_ON = param.get().lower() != "staged"


from modin_tpu.config import FuseMode as _FuseMode  # noqa: E402

_FuseMode.subscribe(_on_fuse_mode)


# ---------------------------------------------------------------------- #
# adaptive padding buckets (compile-ledger storm feedback)
# ---------------------------------------------------------------------- #

#: below this padded length buckets never apply: tiny frames compile in
#: microseconds and unit tests stay byte-for-byte at exact padding
_BUCKET_FLOOR = 1024

#: own-compile thresholds for escalating a signature's bucket level
_STORM_COMPILES = 3

#: bound on tracked signatures: Map payloads embed literal scalar operands
#: (``df.query("a > X")`` with a per-request constant is a fresh signature
#: each time), so the registry is LRU-capped like every other per-key
#: registry in this repo (tenants, scan cache, _FUSED_CACHE) — evicting a
#: cold signature merely restarts its storm counter at exact padding
_MAX_STORM_SIGS = 512

_storm_lock = named_lock("plan.storm")
#: plan signature -> [backend compiles observed during its fused
#: dispatches, {distinct physical input sizes dispatched}]; LRU order
_sig_state: "OrderedDict[Any, list]" = OrderedDict()


def note_fused_compiles(sig: Any, p: int, compiles: int) -> None:
    """Record one fused dispatch's compile delta for ``sig`` at physical
    size ``p`` (the adaptive bucket chooser's own feedback channel)."""
    with _storm_lock:
        state = _sig_state.get(sig)
        if state is None:
            state = _sig_state[sig] = [0, set()]
        else:
            _sig_state.move_to_end(sig)
        state[0] += int(compiles)
        state[1].add(int(p))
        while len(_sig_state) > _MAX_STORM_SIGS:
            _sig_state.popitem(last=False)


def storm_level(sig: Any) -> int:
    """0 = exact padding, 1 = eighth-octave buckets, 2 = pow2 buckets.

    Escalates on the signature's OWN compile count, cross-checked against
    the compile ledger: when the ledger reports the fused span signature
    (``fuse.lower``) as a recompile storm AND this signature itself has
    re-compiled across at least two distinct physical sizes, it escalates
    early.  The per-sig churn requirement matters: every fused lowering
    bills its compiles to the ONE ``fuse.lower`` ledger entry, so three
    unrelated plans cold-compiling once each would otherwise read as a
    storm and start padding healthy workloads.
    """
    with _storm_lock:
        state = _sig_state.get(sig)
        own = state[0] if state else 0
        shapes = len(state[1]) if state else 0
    if own >= 3 * _STORM_COMPILES:
        return 2
    if own >= _STORM_COMPILES:
        return 1
    if shapes >= 2 and own >= 2:
        storms = get_compile_ledger().recompile_storms(_STORM_COMPILES)
        if "fuse.lower" in storms:
            return 1
    return 0


def reset_storm_state() -> None:
    """Forget all storm bookkeeping (tests)."""
    with _storm_lock:
        _sig_state.clear()


def quantize_padded(p: int, level: int) -> int:
    """Bucketed padded length for one physical size at a storm level."""
    p = int(p)
    if level <= 0 or p < _BUCKET_FLOOR:
        return p
    pow2 = 1 << max(p - 1, 1).bit_length()  # smallest pow2 >= p
    if level >= 2:
        return pow2
    step = max(pow2 // 8, 1)  # eighth-octave: <= 12.5% pad waste
    return ((p + step - 1) // step) * step


def _quantizer_for(sig: Any):
    """The ``pad_bucket_scope`` quantizer for this signature, or None while
    the signature has not stormed (exact padding, zero waste)."""
    level = storm_level(sig)
    if level <= 0:
        return None

    def quantize(p: int) -> int:
        q = quantize_padded(p, level)
        if q > p:
            emit_metric("fuse.bucket.quantized", q - p)
        return q

    return quantize


def stream_bucket(m: int) -> int:
    """graftstream hook: double the window row bucket while the fused
    window programs themselves storm (all windows share one signature), so
    a stream of near-boundary ragged windows collapses onto fewer
    executables instead of compiling per pow2 neighbor."""
    return m * 2 if storm_level("stream.window") else m


def segment_signature(root: PlanNode) -> Tuple:
    """Stable (cross-query) identity of a plan segment: node kinds and
    payloads, leaf identities erased.  Keys the storm bookkeeping and the
    ``decide_compile`` span attribution."""
    return tuple(
        (node.kind, () if isinstance(node, (Scan, Source)) else node.payload_key())
        for node in walk(root)
    )


# ---------------------------------------------------------------------- #
# segment extraction + the masked chain walk
# ---------------------------------------------------------------------- #


def _segment_leaf(root: PlanNode) -> Optional[PlanNode]:
    """The ONE Scan/Source leaf under a pure Project/Filter/Map interior
    (the root itself excepted), or None when the shape cannot fuse."""
    leaf = None
    for node in walk(root):
        if isinstance(node, (Scan, Source)):
            if leaf is not None and node is not leaf:
                return None
            leaf = node
        elif node is root:
            continue
        elif isinstance(node, Map):
            if node.method not in _SUPPORTED_MAP_METHODS:
                return None
        elif not isinstance(node, (Project, Filter)):
            return None
    return leaf


def _walk_masked(node: PlanNode, memo: Dict[int, Any], masked: Dict[int, Any]):
    """(unfiltered eager compiler, accumulated keep mask | None) per node.

    The graftfuse replay of the plan chain: Projects and Maps run through
    the SAME eager query-compiler methods the staged lowering uses (their
    device paths build deferred LazyExpr columns — no dispatch), but a
    Filter never compacts: its mask lowers to a deferred boolean expression
    AND-ed into the accumulated keep mask, and the child's columns stay
    full-length.  Valid because every interior op is elementwise: a mask
    computed over original rows selects exactly the rows a staged
    compaction would have kept, in the same order.  Diamond-shared nodes
    (the same Filter reached through an operand subplan) memoize, which is
    also what makes the mask-consistency identity check sound.
    """
    hit = masked.get(id(node))
    if hit is not None:
        return hit
    if isinstance(node, (Scan, Source)):
        from modin_tpu.plan import lowering

        result = (lowering._lower(node, memo), None)
    elif isinstance(node, Project):
        child, mask = _walk_masked(node.children[0], memo, masked)
        qc = child.getitem_column_array(list(node.keys), numeric=node.numeric)
        if node.out_hint is not None:
            qc._shape_hint = node.out_hint
        result = (qc, mask)
    elif isinstance(node, Map):
        receiver, mask = _walk_masked(node.children[0], memo, masked)
        args = []
        for a in node.args:
            if isinstance(a, Ref):
                operand, operand_mask = _walk_masked(
                    node.children[a.index], memo, masked
                )
                if operand_mask is not mask:
                    # operands must have seen the SAME filters; identity
                    # holds for legal plans because the shared Filter node
                    # memoizes to one mask expression
                    raise _Decline("operand filter mismatch")
                args.append(operand)
            else:
                args.append(a)
        qc = getattr(receiver, node.method)(*args, **node.kwargs)
        if node.out_hint is not None:
            qc._shape_hint = node.out_hint
        result = (qc, mask)
    elif isinstance(node, Filter):
        child, mask = _walk_masked(node.children[0], memo, masked)
        mask_qc, mask_below = _walk_masked(node.children[1], memo, masked)
        if mask_below is not mask:
            raise _Decline("mask filter mismatch")
        mframe = mask_qc._modin_frame
        if mframe.num_cols != 1:
            raise _Decline("non-column mask")
        mcol = mframe.get_column(0)
        if not getattr(mcol, "is_device", False) or mcol.pandas_dtype != np.dtype(
            bool
        ):
            raise _Decline("mask not a device bool column")
        from modin_tpu.ops.lazy import lazy_op

        mexpr = mcol.raw
        combined = mexpr if mask is None else lazy_op("__and__", mask, mexpr)
        result = (child, combined)
    else:
        raise _Decline(f"unsupported node {node.kind}")
    masked[id(node)] = result
    return result


def _donation_candidates(frame: Any) -> List[Any]:
    """Leaf columns whose buffers may ride in donated positions.

    Requires the device ledger's sole-consumer proof plus a lineage
    restore path (``DeviceColumn.donation_safe``); disabled entirely while
    a serving context is active — a concurrent query may hold the buffer
    in a pending argument tree the ledger cannot see.
    """
    if serving_context.CONTEXT_ON:
        return []
    candidates = [
        col
        for col in frame._columns
        if getattr(col, "is_device", False) and col.donation_eligible()
    ]
    if not candidates:
        return []
    from modin_tpu.core.memory import device_ledger

    # one ledger walk for the whole batch (not one per column)
    counts = device_ledger.buffer_consumer_counts(
        [col._data for col in candidates]
    )
    return [col for col in candidates if counts.get(id(col._data), 0) == 1]


# ---------------------------------------------------------------------- #
# the fused lowering leg (called from plan/lowering.py)
# ---------------------------------------------------------------------- #


def maybe_fuse_reduce(node: Reduce, memo: Dict[int, Any]) -> Optional[Any]:
    return _maybe_fuse(node, memo, groupby=False)


def maybe_fuse_groupby(node: GroupbyAgg, memo: Dict[int, Any]) -> Optional[Any]:
    return _maybe_fuse(node, memo, groupby=True)


def _maybe_fuse(node: PlanNode, memo: Dict[int, Any], groupby: bool) -> Optional[Any]:
    if not FUSE_ON:
        return None
    if groupby:
        # Ref-grouper (a deferred subplan as the by) stays staged
        if isinstance(node.by, Ref):
            return None
        if not _gate_groupby_kwargs(node):
            return None
    elif node.method not in SUPPORTED_REDUCE:
        return None
    leaf = _segment_leaf(node)
    if leaf is None:
        return None
    sig = segment_signature(node)
    from modin_tpu.ops import router
    from modin_tpu.ops.structural import pad_bucket_scope

    # lower the leaf through the normal memoized path (scan cache, io
    # lineage, spans intact) with the adaptive pad bucket active: a
    # storming signature's next upload lands on a shared physical size
    with pad_bucket_scope(_quantizer_for(sig) if id(leaf) not in memo else None):
        from modin_tpu.plan import lowering

        leaf_qc = lowering._lower(leaf, memo)
    frame = leaf_qc._modin_frame
    n = len(frame)
    if router.decide_compile(sig, n) != "fused":
        return None
    if n == 0 or not frame.all_device:
        # pandas empty/object semantics live with the staged path
        return None
    try:
        qc_top, mask = _walk_masked(node.children[0], memo, {})
    except _Decline:
        emit_metric("fuse.decline", 1)
        return None
    p_in = max(
        (
            int(data.shape[0])
            for c in frame._columns
            if c.is_device and (data := getattr(c, "_data", None)) is not None
            and hasattr(data, "shape")
        ),
        default=0,
    )
    donate_cols = _donation_candidates(frame)
    if donate_cols:
        # graftopt joint constraint: a plan the optimizer marked
        # memory-pressured (windowed tail, re-planned segment) must not
        # donate — the window loop / re-lowering still owns those buffers
        from modin_tpu.plan import optimizer as graftopt

        if not graftopt.donate_ok():
            donate_cols = []
    compiles_before = compiles_on_this_thread()
    with graftscope.span(
        "fuse.lower",
        layer="QUERY-COMPILER",
        sig=f"{hash(sig) & 0xFFFFFFFF:08x}",
        rows=n,
        donated=len(donate_cols),
    ):
        if groupby:
            result = _fused_groupby(node, qc_top, mask, n, donate_cols)
        else:
            result = _fused_reduce(node, qc_top, mask, donate_cols)
    note_fused_compiles(sig, p_in, compiles_on_this_thread() - compiles_before)
    if result is None:
        emit_metric("fuse.decline", 1)
        return None
    emit_metric("fuse.dispatch", 1)
    return result


def _fused_reduce(
    node: Reduce, qc_top: Any, mask: Any, donate_cols: List[Any]
) -> Optional[Any]:
    kwargs = dict(node.call_kwargs)
    axis = kwargs.pop("axis", 0)
    skipna = kwargs.pop("skipna", True)
    numeric_only = kwargs.pop("numeric_only", False)
    if axis not in (0, None):
        return None
    return qc_top._try_device_reduce(
        node.method, axis, skipna, numeric_only, kwargs,
        keep=mask, donate_cols=donate_cols,
    )


def _gate_groupby_kwargs(node: GroupbyAgg) -> bool:
    """Whether this groupby's kwargs are the fused scatter path's exact
    semantics: axis 0, as_index+sort defaults, a single string aggregation
    from the scatter-expressible set over a plain label grouper."""
    from modin_tpu.ops.groupby import FUSED_GROUPBY_AGGS

    if not isinstance(node.agg_func, str) or node.agg_func not in FUSED_GROUPBY_AGGS:
        return False
    by = node.by
    if isinstance(by, (list, tuple)):
        if len(by) != 1 or not isinstance(by[0], str):
            return False
    elif not isinstance(by, str):
        return False
    ck = node.call_kwargs
    if ck.get("axis", 0) not in (0, None):
        return False
    if ck.get("agg_args") or ck.get("series_groupby") or ck.get("selection") is not None:
        return False
    if ck.get("how", "axis_wise") != "axis_wise":
        return False
    gk = ck.get("groupby_kwargs") or {}
    if not set(gk) <= {"as_index", "sort", "dropna", "observed", "group_keys", "level"}:
        return False
    if gk.get("level") is not None:
        return False
    if not gk.get("as_index", True) or not gk.get("sort", True):
        return False
    ak = ck.get("agg_kwargs") or {}
    if not set(ak) <= {"numeric_only", "min_count"}:
        return False
    if ak.get("min_count", 0) not in (0, -1):
        return False
    return True


#: pandas groupby output dtype per aggregation (measured, pandas 2.x):
#: sum/prod keep the column dtype except bool -> int64; count is int64;
#: mean is float64 except float32 stays float32; min/max keep the dtype
def _groupby_out_dtype(agg: str, dtype: np.dtype) -> np.dtype:
    if agg == "count":
        return np.dtype(np.int64)
    if agg == "mean":
        return dtype if dtype == np.dtype(np.float32) else np.dtype(np.float64)
    if agg in ("sum", "prod") and dtype == np.dtype(bool):
        return np.dtype(np.int64)
    return dtype


def _fused_groupby(
    node: GroupbyAgg, qc_top: Any, mask: Any, n: int, donate_cols: List[Any]
) -> Optional[Any]:
    from modin_tpu.ops import groupby as gb

    agg = node.agg_func
    by = node.by if isinstance(node.by, str) else node.by[0]
    frame = qc_top._modin_frame
    columns = list(frame.columns)
    if by not in columns or columns.count(by) != 1:
        return None
    key_pos = columns.index(by)
    key_col = frame._columns[key_pos]
    if not getattr(key_col, "is_device", False) or key_col.pandas_dtype.kind not in "iub":
        return None
    numeric_only = (node.call_kwargs.get("agg_kwargs") or {}).get(
        "numeric_only", False
    )
    value_pos = []
    for i, col in enumerate(frame._columns):
        if i == key_pos:
            continue
        if not getattr(col, "is_device", False) or col.pandas_dtype.kind not in "iufb":
            if numeric_only:
                continue  # numeric_only drops non-numeric columns exactly
                # like the staged path would
            return None
        value_pos.append(i)
    if not value_pos:
        return None
    value_cols = [frame._columns[i] for i in value_pos]

    kmin, kmax, kept = gb.fused_group_probe(key_col.raw, mask, n)
    if kept == 0:
        return None
    width = kmax - kmin + 1
    if width > gb.FUSED_MAX_GROUPS:
        return None
    buckets = gb.fused_groups_bucket(width)
    sizes, tables, _counts = gb.fused_group_agg(
        agg,
        key_col.raw,
        [c.raw for c in value_cols],
        mask,
        n,
        kmin,
        buckets,
        donate_cols=donate_cols,
    )
    observed = np.nonzero(sizes[:buckets] > 0)[0]
    keys = (kmin + observed).astype(key_col.pandas_dtype)
    data = {}
    for pos, table in zip(value_pos, tables):
        out_dtype = _groupby_out_dtype(agg, frame._columns[pos].pandas_dtype)
        data[columns[pos]] = np.asarray(table[:buckets])[observed].astype(
            out_dtype
        )
    result = pandas.DataFrame(
        data,
        index=pandas.Index(keys, name=by),
        columns=[columns[i] for i in value_pos],
    )
    return type(qc_top).from_pandas(result)


# ---------------------------------------------------------------------- #
# graftstream integration: fused window bodies
# ---------------------------------------------------------------------- #


def window_reduce_plan(node: Reduce, scan_node: Any, call_kwargs: dict):
    """Per-STREAM precomputation for the fused window body, or None when
    the chain can never fuse.

    Returns ``run(window_qc) -> reduced compiler | None``: one window's
    chain + reduction as a single masked fused program.  The streaming
    executor's staged window body host-compacts every filter and
    neutral-pads the logical length so ragged windows share programs; the
    masked form needs neither — the physical size is already the window's
    pow2 bucket and the logical length rides as a runtime scalar, so every
    same-bucket window re-dispatches ONE program.  Everything
    stream-invariant (segment shape gate, signature, kwargs parse, the
    compile-router verdict) is computed once here, not once per window;
    ``run`` answers None per window to keep the staged body (zero kept
    rows, unsupported dtypes).
    """
    if not FUSE_ON or node.method not in SUPPORTED_REDUCE:
        return None
    if _segment_leaf(node) is None:
        return None
    kwargs = dict(call_kwargs)
    axis = kwargs.pop("axis", 0)
    skipna = kwargs.pop("skipna", True)
    numeric_only = kwargs.pop("numeric_only", False)
    if axis not in (0, None):
        return None
    sig = segment_signature(node)
    chain = node.children[0]
    from modin_tpu.ops import router

    # windows share one size (the final ragged one shares its bucket), so
    # the routing verdict is decided on the first window and memoized
    verdict: List[bool] = []

    def run(window_qc: Any) -> Optional[Any]:
        frame = window_qc._modin_frame
        if not verdict:
            verdict.append(router.decide_compile(sig, len(frame)) == "fused")
        if not verdict[0] or not frame.all_device:
            return None
        try:
            qc_top, mask = _walk_masked(chain, {id(scan_node): window_qc}, {})
        except _Decline:
            return None
        if mask is None:
            return None  # unfiltered windows: the quantized staged body
            # is already one cached program per bucket
        compiles_before = compiles_on_this_thread()
        result = qc_top._try_device_reduce(
            node.method, axis, skipna, numeric_only, dict(kwargs), keep=mask
        )
        p_in = max(
            (
                int(data.shape[0])
                for c in frame._columns
                if getattr(c, "is_device", False)
                and (data := getattr(c, "_data", None)) is not None
                and hasattr(data, "shape")
            ),
            default=0,
        )
        note_fused_compiles(
            "stream.window", p_in, compiles_on_this_thread() - compiles_before
        )
        if result is not None:
            emit_metric("fuse.dispatch", 1)
        return result

    return run
