"""Zero-copy export/import of the underlying device buffers.

Reference design: modin/distributed/dataframe/pandas/partitions.py:58,154
(``unwrap_partitions``/``from_partitions`` expose raw partition futures for
third-party integrations like xgboost).  The TPU-native equivalent exposes the
sharded jax.Arrays themselves: a consumer can feed them straight into its own
jit-compiled computation with no host round-trip.
"""

from __future__ import annotations

from typing import Any, List, Optional

import numpy as np
import pandas


def unwrap_partitions(api_layer_object: Any, axis: Optional[int] = None, get_ip: bool = False) -> List:
    """Expose the frame's underlying buffers.

    For the Tpu backend returns ``[(label, jax.Array | host_array), ...]`` —
    the live (possibly sharded) device columns, zero-copy.  For host backends
    returns the column arrays.  With ``get_ip=True`` each element becomes
    ``(location, (label, data))`` — the reference's ``(ip, partition)`` shape
    (partitions.py:58), where the locality token is the set of devices the
    buffer lives on ("host" for host columns).
    """
    qc = api_layer_object._query_compiler
    frame = getattr(qc, "_modin_frame", None)
    result = []
    if frame is not None and hasattr(frame, "_columns"):
        for label, col in zip(frame.columns, frame._columns):
            data = col.data
            if get_ip:
                if col.is_device:
                    devices = sorted(
                        str(d) for d in getattr(data.sharding, "device_set", ())
                    )
                    location = ",".join(devices) or "host"
                else:
                    location = "host"
                result.append((location, (label, data)))
            else:
                result.append((label, data))
        return result
    pandas_df = qc.to_pandas()
    if get_ip:
        return [
            ("host", (label, pandas_df[label].to_numpy()))
            for label in pandas_df.columns
        ]
    return [(label, pandas_df[label].to_numpy()) for label in pandas_df.columns]


def from_partitions(
    partitions: List,
    axis: Optional[int] = None,
    index: Any = None,
    columns: Any = None,
    row_lengths: Any = None,
    column_widths: Any = None,
) -> Any:
    """Build a DataFrame from raw per-column buffers (jax.Arrays or numpy).

    The inverse of :func:`unwrap_partitions`: device arrays are adopted
    without a host round-trip.
    """
    from modin_tpu.core.dataframe.tpu.dataframe import (
        DeviceColumn,
        HostColumn,
        TpuDataframe,
    )
    from modin_tpu.core.dataframe.tpu.metadata import LazyIndex
    from modin_tpu.core.storage_formats.tpu.query_compiler import TpuQueryCompiler
    from modin_tpu.ops.structural import pad_host, pad_len
    from modin_tpu.pandas.dataframe import DataFrame

    try:
        import jax

        jax_array_type = jax.Array
    except ImportError:  # pragma: no cover
        jax_array_type = ()

    def _normalize(i, item):
        if isinstance(item, tuple) and len(item) == 2:
            # (location, (label, data)) from unwrap_partitions(get_ip=True):
            # drop the locality token and keep the labelled buffer
            if isinstance(item[1], tuple) and len(item[1]) == 2:
                return item[1]
            return item
        return (i, item)

    pairs = [_normalize(i, item) for i, item in enumerate(partitions)]
    # the logical length: the index wins; otherwise the first host buffer;
    # otherwise a raw device buffer is taken as exactly-logical
    if index is not None:
        n = len(index)
    else:
        n = None
        for _, data in pairs:
            if not isinstance(data, jax_array_type):
                n = len(np.asarray(data))
                break
        if n is None and pairs:
            n = int(pairs[0][1].shape[0])
    if n is None:
        n = 0

    labels = []
    cols = []
    for label, data in pairs:
        labels.append(label)
        if isinstance(data, jax_array_type):
            if data.shape[0] == pad_len(n):
                # already in the padded shard layout: adopt zero-copy
                cols.append(DeviceColumn(data, np.dtype(str(data.dtype)), length=n))
            else:
                cols.append(DeviceColumn.from_numpy(np.asarray(data)[:n]))
        else:
            arr = np.asarray(data)
            if arr.dtype.kind in "biufmM":
                cols.append(DeviceColumn.from_numpy(arr))
            else:
                cols.append(HostColumn(pandas.array(arr)))
    if index is None:
        index = pandas.RangeIndex(n)
    frame = TpuDataframe(
        cols,
        pandas.Index(columns if columns is not None else labels),
        LazyIndex(pandas.Index(index) if not isinstance(index, pandas.Index) else index, n),
        nrows=n,
    )
    return DataFrame(query_compiler=TpuQueryCompiler(frame))
