"""Raw-buffer export/import (reference: modin/distributed/dataframe/pandas/)."""

from modin_tpu.distributed.dataframe.pandas.partitions import (  # noqa: F401
    from_partitions,
    unwrap_partitions,
)
