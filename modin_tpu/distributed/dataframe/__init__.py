"""Distributed dataframe exchange API."""
