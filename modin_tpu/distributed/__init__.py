"""Distributed public API (reference: modin/distributed/)."""
