"""graftwal — durable ingestion for graftfeed.

Write-ahead log + crash-consistent checkpoints + bit-exact replay
recovery.  Entry points:

- ``modin_tpu.ingest.open_feed(name, ..., durable=True)`` — the public
  door; it lazy-imports this package, so a process that never opens a
  durable feed never pays a byte for it (the zero-overhead contract,
  asserted via :data:`DURABILITY_ON` + :func:`durability_alloc_count`
  exactly like the graftscope contract);
- :func:`recover_feeds` — the graftfleet replica warm path: open every
  durable feed found under a root directory;
- :class:`DurabilityError` — the one typed refusal.
"""

from __future__ import annotations

#: flips True on the first durable-feed open; the zero-overhead assert
#: for non-durable workloads checks this stays False.
DURABILITY_ON = False

_alloc_count = 0


def _note_alloc() -> None:
    """Count durability-object constructions — the zero-overhead proof
    hook (mirrors ingest.live.note_alloc / the graftscope contract)."""
    global _alloc_count
    _alloc_count += 1


def durability_alloc_count() -> int:
    return _alloc_count


def _mark_active() -> None:
    global DURABILITY_ON
    DURABILITY_ON = True


from modin_tpu.durability.errors import DurabilityError  # noqa: E402
from modin_tpu.durability.manager import (  # noqa: E402
    FeedDurability,
    open_durable_feed,
    recover_feeds,
    resolve_root_dir,
)

__all__ = [
    "DURABILITY_ON",
    "DurabilityError",
    "FeedDurability",
    "durability_alloc_count",
    "open_durable_feed",
    "recover_feeds",
    "resolve_root_dir",
]
