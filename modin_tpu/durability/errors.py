"""graftwal typed errors.

Deliberate near-leaf module: only the (leaf) ingest error types are
imported, so serving / fleet / test code may reference the durability
error without pulling the WAL machinery in.
"""

from __future__ import annotations

from modin_tpu.ingest.errors import IngestError


class DurabilityError(IngestError):
    """A durability operation failed in a way the subsystem will not
    paper over.  ``reason`` is a stable slug so callers can branch
    without parsing the message:

    - ``enospc`` — the WAL write hit ENOSPC and a retention-driven
      segment reclaim did not free enough space; the batch was REFUSED
      before any in-memory mutation (retry after freeing disk);
    - ``schema_mismatch`` — ``open_feed`` was given a schema that
      contradicts the on-disk ``meta.json`` (or a WAL record's schema
      tag contradicts the feed it replays into);
    - ``corrupt_meta`` — the feed's ``meta.json`` is unreadable, so the
      feed cannot be reconstructed without an explicit schema;
    - ``not_durable`` — a durability operation was requested on a feed
      that has no WAL attached.

    EIO-class write failures do NOT raise this: they trip the per-feed
    breaker into memory-only degraded mode (``wal.degraded``) because
    refusing ingestion would turn a lost disk into a lost service.
    """

    def __init__(self, feed: str, reason: str, detail: str = "") -> None:
        self.feed = feed
        self.reason = reason
        msg = f"feed {feed!r} durability failure: {reason}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
