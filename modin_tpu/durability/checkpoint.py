"""graftwal checkpoints: crash-consistent snapshots of a feed + its views.

A checkpoint file ``ckpt_<wal_seq>.ckpt`` holds one pickled snapshot of
everything a feed would lose in a crash: the retained mirror frame, the
key index, the batch log spine (seq / rows / abs_start — the row data is
already in the mirror), and every registered view's complete fold state
(bootstrap partial, per-batch partials, running state — the same
foldable state graftview/live.py maintains).  ``wal_seq`` in the name is
the newest WAL record the snapshot covers: recovery loads the newest
valid checkpoint and replays only records past it, which is what bounds
replay time by ``MODIN_TPU_WAL_MAX_REPLAY_BATCHES``.

File format: ``[u32 crc32(payload)][payload]`` written through the
shared atomic helper (temp file + fsync + rename + directory fsync), so
a reader sees an old complete checkpoint or a new complete one — never a
prefix.  A CRC or unpickle failure at load time returns None
(``checkpoint.invalid``) and recovery falls back to the next-older file
instead of crashing.
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

from modin_tpu.durability import wal as _wal
from modin_tpu.utils.atomic_io import atomic_write_bytes

CKPT_PREFIX = "ckpt_"
CKPT_SUFFIX = ".ckpt"

_CKPT_HEADER = struct.Struct("<I")  # crc32(payload)


def checkpoint_path(feed_dir: str, wal_seq: int) -> str:
    return os.path.join(feed_dir, f"{CKPT_PREFIX}{wal_seq:016d}{CKPT_SUFFIX}")


def list_checkpoints(feed_dir: str) -> List[Tuple[int, str]]:
    """``[(wal_seq, path)]`` ascending; ignores foreign files."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(feed_dir)
    except OSError:
        return out
    for fname in names:
        if not (fname.startswith(CKPT_PREFIX) and fname.endswith(CKPT_SUFFIX)):
            continue
        digits = fname[len(CKPT_PREFIX):-len(CKPT_SUFFIX)]
        try:
            seq = int(digits)
        except ValueError:
            continue
        out.append((seq, os.path.join(feed_dir, fname)))
    out.sort()
    return out


def serialize_snapshot(snapshot: Dict[str, Any]) -> bytes:
    """Pickle OUTSIDE any registry lock (graftdep LOCK-BLOCKING)."""
    return pickle.dumps(snapshot, protocol=pickle.HIGHEST_PROTOCOL)


def write_checkpoint(feed_dir: str, wal_seq: int, payload: bytes) -> str:
    """Atomically write one checkpoint; returns its path.  Raises OSError
    on disk failure (the caller decides: reclaim-and-retry or give up —
    the WAL still holds every record, so a failed checkpoint loses
    nothing but replay time)."""
    _wal.disk_op("checkpoint.write")
    path = checkpoint_path(feed_dir, wal_seq)
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    atomic_write_bytes(
        path, _CKPT_HEADER.pack(crc) + payload, durable_rename=True
    )
    return path


def load_checkpoint(path: str) -> Optional[Dict[str, Any]]:
    """The snapshot dict, or None when the file is unreadable, fails its
    CRC, or does not unpickle — recovery treats None as 'try the next
    older checkpoint', never a crash."""
    try:
        with open(path, "rb") as f:
            data = f.read()
        if len(data) < _CKPT_HEADER.size:
            return None
        (crc,) = _CKPT_HEADER.unpack_from(data, 0)
        payload = data[_CKPT_HEADER.size:]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return None
        snapshot = pickle.loads(payload)
    except (OSError, ValueError, EOFError, pickle.UnpicklingError, AttributeError, ImportError, IndexError):
        return None
    return snapshot if isinstance(snapshot, dict) else None
