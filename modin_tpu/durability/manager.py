"""graftwal per-feed durability manager: WAL hooks, checkpoints, recovery.

:class:`FeedDurability` is the object a durable feed carries as its
``_wal`` attribute.  Division of labour with ingest/feed.py:

- ``encode_batch`` / ``encode_register`` run OUTSIDE every lock (pickle
  is a graftdep LOCK-BLOCKING operation) and return ``None`` when the
  feed is degraded or mid-replay — the hot path then skips logging with
  a single ``is None`` check;
- ``log_encoded`` runs UNDER the feed rlock, *before* the in-memory
  mutation the record describes (write-ahead by construction); a
  :class:`~modin_tpu.durability.errors.DurabilityError` raised here
  refuses the batch with the feed state untouched;
- ``maybe_checkpoint`` runs after the feed lock releases and snapshots
  the feed + every view's fold state once the WAL tail exceeds
  ``MODIN_TPU_WAL_MAX_REPLAY_BATCHES`` records (the replay-time bound);
- ``recover`` rebuilds the in-memory feed from the newest valid
  checkpoint plus a WAL-tail replay through the ORDINARY ingest path —
  sequence numbers make the replay idempotent, and a torn tail is
  truncated with ``wal.torn_tail`` accounting, never a crash.

Metric fan-out discipline: every method collects ``(name, value)``
events and flushes them through :meth:`fanout` after all locks release
(the PR 9 gate-lock lesson); the fan-out body is one literal
``emit_metric`` call per metric family so REGISTRY-DRIFT sees live emit
sites for each declared name.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from modin_tpu.durability import checkpoint as ckpt
from modin_tpu.durability import wal
from modin_tpu.durability.errors import DurabilityError
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability.spans import span
from modin_tpu.utils.atomic_io import atomic_write_json

_META_NAME = "meta.json"

Events = List[Tuple[str, int]]


def _config():
    import modin_tpu.config as config

    return config


class FeedDurability:
    """One durable feed's WAL writer + checkpointer + recovery engine."""

    def __init__(self, feed: Any, feed_dir: str) -> None:
        from modin_tpu.durability import _note_alloc

        _note_alloc()
        config = _config()
        self._feed = feed
        self.feed_dir = feed_dir
        self.tag = wal.schema_tag(feed.schema)
        self.policy = str(config.WalFsync.get())
        self.group_ms = float(config.WalGroupCommitMs.get())
        self.max_replay = int(config.WalMaxReplayBatches.get())
        self.writer = wal.SegmentWriter(
            feed.name,
            feed_dir,
            0,
            self.policy,
            int(config.WalSegmentBytes.get()),
            self._reclaim_under_wal_lock,
        )
        #: newest wal_seq applied to the in-memory feed (feed rlock)
        self._applied_seq = -1
        #: wal_seq the newest durable checkpoint covers
        self._ckpt_seq = -1
        self._ckpt_claimed = False  # writer lock guards the claim flag
        self._replaying = False
        self.replayed_batches = 0  # last recovery's replay count (tests)
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None
        self._obs_span_stack: Any = None
        self._obs_scopes: Any = None

    @property
    def degraded(self) -> bool:
        return self.writer.degraded

    # -- hot-path hooks (called from ingest/feed.py) -------------------- #

    def encode_batch(self, pdf: Any, is_upsert: bool) -> Optional[Tuple[int, bytes]]:
        """Serialize one admitted micro-batch OUTSIDE any lock; ``None``
        means 'nothing to log' (degraded breaker open, or this batch IS
        a replay and logging it again would double it)."""
        if self.writer.degraded or self._replaying or not len(pdf):
            return None
        return wal.encode_batch(self.tag, pdf, is_upsert)

    def encode_register(self, name: str, plan: Dict[str, Any]) -> Optional[Tuple[int, bytes]]:
        if self.writer.degraded or self._replaying:
            return None
        return wal.encode_register(self.tag, name, plan)

    def log_encoded(self, encoded: Tuple[int, bytes], events: Events) -> None:
        """Append one pre-encoded record — the caller holds the feed
        rlock and has NOT yet mutated feed state.  DurabilityError
        (exhausted ENOSPC) propagates: the batch is refused whole."""
        opcode, payload = encoded
        seq = self.writer.append(opcode, payload, events)
        if seq is not None:
            self._applied_seq = seq

    def maybe_checkpoint(self) -> bool:
        """Checkpoint when the un-checkpointed WAL tail exceeds the
        replay bound.  Called after the feed lock releases."""
        if self._replaying:
            return False
        if self._applied_seq - self._ckpt_seq < self.max_replay:
            return False
        return self.checkpoint()

    # -- checkpoints ---------------------------------------------------- #

    def _try_claim_checkpoint(self) -> bool:
        with self.writer._lock:
            if self._ckpt_claimed:
                return False
            self._ckpt_claimed = True
            return True

    def _release_checkpoint(self) -> None:
        with self.writer._lock:
            self._ckpt_claimed = False

    def checkpoint(self) -> bool:
        """Write one crash-consistent snapshot (feed frame + every view's
        fold state), then truncate WAL segments it fully covers.  Returns
        True when a checkpoint landed.  A disk failure here loses nothing
        — the WAL still holds every record — so it degrades replay time,
        not correctness, and is reported by the absence of
        ``checkpoint.write`` progress."""
        if self.writer.degraded or not self._try_claim_checkpoint():
            return False
        events: Events = []
        wrote = False
        try:
            with span("checkpoint.write", layer="APP", feed=self._feed.name):
                snapshot = self._snapshot()
                if snapshot is None:
                    return False
                payload = ckpt.serialize_snapshot(snapshot)  # outside locks
                try:
                    ckpt.write_checkpoint(
                        self.feed_dir, snapshot["wal_seq"], payload
                    )
                except OSError:
                    return False
                wrote = True
                self._ckpt_seq = snapshot["wal_seq"]
                events.append(("checkpoint.write", 1))
                events.append(("checkpoint.bytes", len(payload)))
                self._truncate_covered(events)
        finally:
            self._release_checkpoint()
            self.fanout(events)
        return wrote

    def _snapshot(self) -> Optional[Dict[str, Any]]:
        """Copy everything recovery needs, under the feed rlock.  The
        mirror is copied (upserts mutate it in place); view partials and
        states are shared by reference — the fold algebra replaces them
        functionally, never mutates."""
        feed = self._feed
        with feed._lock:
            if self._applied_seq < 0:
                return None
            feed._fold_pending_locked()
            views: Dict[str, Dict[str, Any]] = {}
            for vname, view in feed._views.items():
                views[vname] = {
                    "plan": dict(view.plan),
                    "bootstrap": view._bootstrap,
                    "bootstrap_seq": view._bootstrap_seq,
                    "partials": OrderedDict(view._partials),
                    "state": view._state,
                    "folded_seq": view.folded_seq,
                    "folds": view.folds,
                    "rebuilds": view.rebuilds,
                    "late_buckets": view.late_buckets,
                }
            return {
                "format": 1,
                "feed": feed.name,
                "schema_tag": self.tag,
                "wal_seq": self._applied_seq,
                "feed_seq": feed._seq,
                "rows": feed._rows,
                "base_offset": feed._base_offset,
                "mirror": feed._mirror.copy(),
                "key_index": dict(feed._key_index),
                "batches": [
                    (rec.seq, rec.rows, rec.abs_start)
                    for rec in feed._batches
                ],
                "views": views,
            }

    def _truncate_covered(self, events: Events) -> None:
        """Delete WAL segments fully covered by the newest checkpoint and
        every older checkpoint file (outside the writer lock)."""
        active = self.writer.active_path()
        removed = self._drop_covered_files(self._ckpt_seq, active, events)
        if removed:
            events.append(("wal.truncate.segments", removed))

    def _reclaim_under_wal_lock(self, events: Events) -> int:
        """ENOSPC reclaim callback — invoked BY the SegmentWriter while it
        holds the ``durability.wal`` lock, so this must not re-take it."""
        return self._drop_covered_files(
            self._ckpt_seq, self.writer._fh_path, events
        )

    def _drop_covered_files(
        self, through_seq: int, active: Optional[str], events: Events
    ) -> int:
        removed = 0
        segments = wal.list_segments(self.feed_dir)
        for i, (first, path) in enumerate(segments):
            if path == active or i + 1 >= len(segments):
                continue  # never the active or the newest segment
            next_first = segments[i + 1][0]
            if next_first <= through_seq + 1:
                try:
                    wal.disk_op("checkpoint.truncate")
                    os.unlink(path)
                    removed += 1
                except OSError:
                    continue
        checkpoints = ckpt.list_checkpoints(self.feed_dir)
        for seq, path in checkpoints[:-1]:  # keep only the newest
            try:
                wal.disk_op("checkpoint.truncate")
                os.unlink(path)
                removed += 1
            except OSError:
                continue
        return removed

    # -- recovery ------------------------------------------------------- #

    def recover(self) -> int:
        """Rebuild the in-memory feed: newest valid checkpoint, then
        replay the WAL tail through the ordinary ingest path.  Runs
        pre-publish (no concurrent appends) under the serving gate as a
        maintenance query.  Returns the number of replayed records."""
        events: Events = []
        replayed = skipped = 0
        feed = self._feed
        try:
            with span("recovery.replay", layer="APP", feed=feed.name):
                snapshot = self._load_newest_checkpoint(events)
                if snapshot is not None:
                    self._restore(snapshot)
                    events.append(("checkpoint.load", 1))
                self._replaying = True
                try:
                    replayed, skipped = self._replay_segments(events)
                finally:
                    self._replaying = False
                self.writer.next_seq = self._applied_seq + 1
                segments = wal.list_segments(self.feed_dir)
                if segments:
                    self.writer.adopt_segment(segments[-1][0])
                if replayed:
                    events.append(("wal.replay.batches", replayed))
                if skipped:
                    events.append(("wal.replay.skipped", skipped))
                events.append(("recovery.feed", 1))
        finally:
            self.fanout(events)
        self.replayed_batches = replayed
        return replayed

    def _load_newest_checkpoint(self, events: Events) -> Optional[Dict[str, Any]]:
        for seq, path in reversed(ckpt.list_checkpoints(self.feed_dir)):
            snapshot = ckpt.load_checkpoint(path)
            if (
                snapshot is None
                or snapshot.get("format") != 1
                or snapshot.get("schema_tag") != self.tag
            ):
                # corrupt, torn-at-rename, or foreign: fall back older
                events.append(("checkpoint.invalid", 1))
                continue
            return snapshot
        return None

    def _restore(self, snapshot: Dict[str, Any]) -> None:
        from modin_tpu.ingest.feed import _BatchRecord
        from modin_tpu.ingest.live import LiveView

        import modin_tpu.pandas as mpd

        feed = self._feed
        with feed._lock:
            feed._mirror = snapshot["mirror"]
            feed._frame = mpd.DataFrame(feed._mirror)
            feed._key_index = dict(snapshot["key_index"])
            feed._seq = snapshot["feed_seq"]
            feed._rows = snapshot["rows"]
            feed._base_offset = snapshot["base_offset"]
            feed._batches = deque(
                _BatchRecord(seq, rows, abs_start, None)
                for seq, rows, abs_start in snapshot["batches"]
            )
            feed._pending = deque()  # a checkpoint is always fully folded
            feed._views = {}
            for vname, vs in snapshot["views"].items():
                view = LiveView(feed.name, vname, vs["plan"], feed.schema)
                view._bootstrap = vs["bootstrap"]
                view._bootstrap_seq = vs["bootstrap_seq"]
                view._partials = OrderedDict(vs["partials"])
                view._state = vs["state"]
                view.folded_seq = vs["folded_seq"]
                view.folds = vs["folds"]
                view.rebuilds = vs["rebuilds"]
                view.late_buckets = vs["late_buckets"]
                feed._views[vname] = view
            self._applied_seq = snapshot["wal_seq"]
            self._ckpt_seq = snapshot["wal_seq"]

    def _replay_segments(self, events: Events) -> Tuple[int, int]:
        from modin_tpu.ingest.errors import IngestRejected

        feed = self._feed
        replayed = skipped = 0
        segments = wal.list_segments(self.feed_dir)
        for i, (first, path) in enumerate(segments):
            records, valid_bytes, torn = wal.read_segment(path)
            for seq, opcode, payload in records:
                if seq <= self._applied_seq:
                    skipped += 1  # the checkpoint already covers it
                    continue
                data = wal.decode_payload(opcode, payload)
                if opcode == wal.OP_REGISTER:
                    tag, vname, plan = data
                    self._check_tag(tag)
                    if vname not in feed._views:
                        feed.register_view(vname, plan)
                else:
                    tag, pdf = data
                    self._check_tag(tag)
                    try:
                        feed._append_sync(pdf, opcode == wal.OP_UPSERT)
                    except IngestRejected:
                        # idempotence backstop: a record the state already
                        # absorbed (e.g. keys present) is skipped, not fatal
                        skipped += 1
                        self._applied_seq = seq
                        continue
                replayed += 1
                self._applied_seq = seq
            if torn:
                # everything past valid_bytes is a crashed writer's
                # garbage; truncate it and drop unreachable later segments
                wal.disk_op("wal.truncate")
                try:
                    os.truncate(path, valid_bytes)
                except OSError:
                    pass
                events.append(("wal.torn_tail", 1))
                dropped = 0
                for _, later in segments[i + 1:]:
                    try:
                        os.unlink(later)
                        dropped += 1
                    except OSError:
                        pass
                if dropped:
                    events.append(("wal.truncate.segments", dropped))
                break
        return replayed, skipped

    def _check_tag(self, tag: int) -> None:
        if tag != self.tag:
            raise DurabilityError(
                self._feed.name,
                "schema_mismatch",
                "WAL record's schema tag contradicts the feed schema",
            )

    # -- group-commit flusher ------------------------------------------- #

    def start(self) -> None:
        """Start the group-commit flusher (GroupCommit policy only)."""
        if self.policy != "GroupCommit" or self._flusher is not None:
            return
        from modin_tpu.observability import meters as graftmeter
        from modin_tpu.observability import spans as graftscope

        self._obs_span_stack = graftscope.snapshot_stack()
        self._obs_scopes = graftmeter.snapshot_scopes()
        thread = threading.Thread(
            target=self._flush_loop,
            name=f"modin-tpu-wal-flush-{self._feed.name}",
            daemon=True,
        )
        self._flusher = thread
        thread.start()

    def _flush_loop(self) -> None:
        from modin_tpu.observability import meters as graftmeter
        from modin_tpu.observability import spans as graftscope

        graftscope.seed_thread(self._obs_span_stack)
        graftmeter.seed_thread_scopes(self._obs_scopes)
        interval_s = max(self.group_ms, 1.0) / 1e3
        while not self._stop.wait(interval_s):
            events: Events = []
            self.writer.flush_if_dirty(events)
            self.fanout(events)
            self.maybe_checkpoint()
        events = []
        self.writer.flush_if_dirty(events)
        self.fanout(events)

    def close(self) -> None:
        """Stop the flusher and close the segment (final fsync included).
        Called OUTSIDE the feeds-table lock — Thread.join under a
        registry lock is a graftdep LOCK-BLOCKING violation."""
        self._stop.set()
        thread = self._flusher
        if thread is not None:
            thread.join(timeout=5.0)
            self._flusher = None
        self.writer.close()

    # -- metric fan-out (after every lock releases) --------------------- #

    def fanout(self, events: Events) -> None:
        if not events:
            return
        totals: Dict[str, int] = {}
        for name, value in events:
            totals[name] = totals.get(name, 0) + value
        value = totals.get("wal.append")
        if value:
            emit_metric("wal.append", value)
        value = totals.get("wal.append.bytes")
        if value:
            emit_metric("wal.append.bytes", value)
        value = totals.get("wal.fsync")
        if value:
            emit_metric("wal.fsync", value)
        value = totals.get("wal.segment.roll")
        if value:
            emit_metric("wal.segment.roll", value)
        value = totals.get("wal.truncate.segments")
        if value:
            emit_metric("wal.truncate.segments", value)
        value = totals.get("wal.torn_tail")
        if value:
            emit_metric("wal.torn_tail", value)
        value = totals.get("wal.degraded")
        if value:
            emit_metric("wal.degraded", value)
        value = totals.get("wal.enospc.reclaim")
        if value:
            emit_metric("wal.enospc.reclaim", value)
        value = totals.get("wal.replay.batches")
        if value:
            emit_metric("wal.replay.batches", value)
        value = totals.get("wal.replay.skipped")
        if value:
            emit_metric("wal.replay.skipped", value)
        value = totals.get("checkpoint.write")
        if value:
            emit_metric("checkpoint.write", value)
        value = totals.get("checkpoint.bytes")
        if value:
            emit_metric("checkpoint.bytes", value)
        value = totals.get("checkpoint.load")
        if value:
            emit_metric("checkpoint.load", value)
        value = totals.get("checkpoint.invalid")
        if value:
            emit_metric("checkpoint.invalid", value)
        value = totals.get("recovery.feed")
        if value:
            emit_metric("recovery.feed", value)


# --------------------------------------------------------------------- #
# durable feed construction + fleet recovery sweep
# --------------------------------------------------------------------- #


def _schema_to_meta(schema: Dict[str, Any]) -> List[List[str]]:
    import numpy as np

    return [[col, np.dtype(dt).str] for col, dt in schema.items()]


def _schema_from_meta(pairs: Any) -> "OrderedDict[str, Any]":
    import numpy as np

    return OrderedDict((col, np.dtype(s)) for col, s in pairs)


def resolve_root_dir(explicit: Optional[str] = None) -> str:
    """The durability root: explicit arg > ``MODIN_TPU_WAL_DIR`` >
    ``<MODIN_TPU_CACHE_DIR>/wal``."""
    if explicit:
        return explicit
    config = _config()
    configured = str(config.WalDir.get())
    if configured:
        return configured
    return os.path.join(str(config.CacheDir.get()), "wal")


def open_durable_feed(
    name: str,
    schema: Optional[Dict[str, Any]] = None,
    key: Optional[str] = None,
    retention_rows: Optional[int] = None,
    retention_age_s: Optional[float] = None,
    root_dir: Optional[str] = None,
) -> Any:
    """Create-or-recover one durable feed (NOT registered in the feeds
    table — :func:`modin_tpu.ingest.open_feed` does that).  A fresh feed
    writes ``meta.json`` atomically; an existing directory is recovered:
    newest valid checkpoint, WAL-tail replay under the serving gate as a
    maintenance query, torn tail truncated with accounting."""
    from modin_tpu.ingest.feed import Feed

    root = resolve_root_dir(root_dir)
    feed_dir = os.path.join(root, name)
    meta_path = os.path.join(feed_dir, _META_NAME)
    existing = os.path.exists(meta_path)
    if existing:
        try:
            with open(meta_path, "r", encoding="utf-8") as f:
                meta = json.load(f)
            disk_schema = _schema_from_meta(meta["schema"])
        except (OSError, ValueError, KeyError, TypeError) as err:
            raise DurabilityError(
                name, "corrupt_meta", f"unreadable {meta_path}: {err}"
            )
        if schema is not None and wal.schema_tag(
            OrderedDict(schema)
        ) != wal.schema_tag(disk_schema):
            raise DurabilityError(
                name,
                "schema_mismatch",
                "supplied schema contradicts the on-disk meta.json",
            )
        schema = disk_schema
        if key is None:
            key = meta.get("key")
        if retention_rows is None:
            retention_rows = meta.get("retention_rows")
        if retention_age_s is None:
            retention_age_s = meta.get("retention_age_s")
    else:
        if schema is None:
            raise DurabilityError(
                name, "corrupt_meta",
                "new durable feed needs an explicit schema",
            )
        os.makedirs(feed_dir, exist_ok=True)
        atomic_write_json(
            meta_path,
            {
                "format": 1,
                "name": name,
                "schema": _schema_to_meta(OrderedDict(schema)),
                "key": key,
                "retention_rows": retention_rows,
                "retention_age_s": retention_age_s,
            },
            durable_rename=True,
        )
    feed = Feed(
        name, schema, key=key,
        retention_rows=retention_rows, retention_age_s=retention_age_s,
    )
    manager = FeedDurability(feed, feed_dir)
    feed._wal = manager
    from modin_tpu import durability as _durability

    _durability._mark_active()
    if existing:
        from modin_tpu import serving

        serving.submit(
            manager.recover,
            tenant="maintenance", label=f"recovery.{name}",
        )
    manager.start()
    return feed


def recover_feeds(root_dir: Optional[str] = None) -> int:
    """Open (and so recover) every durable feed found under the root —
    the fleet-replica warm path.  Feeds already registered are left
    alone.  Returns the number of feeds opened."""
    from modin_tpu import ingest as _ingest

    root = resolve_root_dir(root_dir)
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return 0
    opened = 0
    known = set(_ingest.feeds())
    for name in names:
        if name in known:
            continue
        if not os.path.exists(os.path.join(root, name, _META_NAME)):
            continue
        _ingest.open_feed(name, durable=True, durability_dir=root)
        opened += 1
    return opened
