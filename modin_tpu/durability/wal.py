"""graftwal write-ahead log: record codec + per-feed segment writer.

Record format (little-endian), one record per accepted micro-batch or
view registration:

    [u32 body_len][u32 crc32(body)] [u64 wal_seq][u8 opcode][payload]
    \\------ header (8 bytes) -----/ \\----------- body ------------/

The CRC covers the whole body (sequence number and opcode included), so
a flipped byte anywhere in a record is detected, and a short header or
short body is a torn tail by construction.  ``wal_seq`` increases by
exactly one per record within a feed; recovery replays records with
``wal_seq`` greater than the newest checkpoint's and skips the rest —
that monotonic sequence is what makes replay idempotent.

Payloads are pickled OUTSIDE any registry lock (see
:func:`encode_batch` / :func:`encode_register` — the graftdep
LOCK-BLOCKING contract); only the cheap header build, the single
``write`` call, and the policy fsync run under the feed serialization,
which is exactly the ordering the WAL exists to promise (batch on disk
*before* the in-memory mutation it describes).

Segments are ``wal_<first_seq>.seg`` files; the writer rolls to a new
segment past ``MODIN_TPU_WAL_SEGMENT_BYTES`` and checkpoint truncation
deletes every non-active segment fully covered by a checkpoint.

Failure policy (the decision table lives in docs/architecture.md):

- **ENOSPC** on a record write: the manager's reclaim callback deletes
  checkpoint-covered segments + stale checkpoints, then the write is
  retried once; still failing raises a typed
  :class:`~modin_tpu.durability.errors.DurabilityError` and the batch is
  refused before any in-memory mutation.
- **EIO / any other OSError** (write or fsync): the per-feed breaker
  trips into memory-only degraded mode — ingestion keeps working, the
  ``wal.degraded`` counter says durability is honestly lost, and no
  further disk writes are attempted for this feed.
"""

from __future__ import annotations

import errno
import os
import pickle
import signal
import struct
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

from modin_tpu.concurrency import named_lock
from modin_tpu.durability.errors import DurabilityError

_HEADER = struct.Struct("<II")  # body_len, crc32(body)
_BODY_PREFIX = struct.Struct("<QB")  # wal_seq, opcode

OP_APPEND = 0
OP_UPSERT = 1
OP_REGISTER = 2

SEGMENT_PREFIX = "wal_"
SEGMENT_SUFFIX = ".seg"

#: test seam (testing/faults.DiskFaultInjector): called before every disk
#: operation as ``hook(op)`` with op one of ``wal.write`` / ``wal.fsync``
#: / ``wal.truncate`` / ``checkpoint.write`` / ``checkpoint.truncate``.
#: It may raise ``OSError`` (the fault) or return an ``int`` N — valid
#: only for ``wal.write``: the first N bytes of the record land on disk
#: and the process SIGKILLs itself, a real torn write.
_disk_fault_hook: Optional[Callable[[str], Optional[int]]] = None


def disk_op(op: str) -> Optional[int]:
    """Run the injected-disk-fault seam for ``op`` (None in production)."""
    hook = _disk_fault_hook
    if hook is None:
        return None
    return hook(op)


def schema_tag(schema: Dict[str, Any]) -> int:
    """Stable CRC32 tag of a feed schema (column order + dtype identity);
    stamped into every record and checkpoint so foreign/stale durability
    state is refused instead of replayed."""
    import numpy as np

    text = ";".join(f"{col}={np.dtype(dt).str}" for col, dt in schema.items())
    return zlib.crc32(text.encode("utf-8")) & 0xFFFFFFFF


def encode_batch(tag: int, pdf: Any, is_upsert: bool) -> Tuple[int, bytes]:
    """``(opcode, payload)`` for one normalized micro-batch.  Pickle of
    the schema-exact pandas frame: bit-exact round-trip, and replay
    re-enters the ordinary ingest path with the very frame it admitted."""
    opcode = OP_UPSERT if is_upsert else OP_APPEND
    return opcode, pickle.dumps((tag, pdf), protocol=pickle.HIGHEST_PROTOCOL)


def encode_register(tag: int, name: str, plan: Dict[str, Any]) -> Tuple[int, bytes]:
    """``(opcode, payload)`` for one view registration, so a view
    registered after the newest checkpoint survives a crash too."""
    return OP_REGISTER, pickle.dumps(
        (tag, name, dict(plan)), protocol=pickle.HIGHEST_PROTOCOL
    )


def decode_payload(opcode: int, payload: bytes) -> Any:
    return pickle.loads(payload)


def segment_path(feed_dir: str, first_seq: int) -> str:
    return os.path.join(feed_dir, f"{SEGMENT_PREFIX}{first_seq:016d}{SEGMENT_SUFFIX}")


def list_segments(feed_dir: str) -> List[Tuple[int, str]]:
    """``[(first_seq, path)]`` ascending; ignores foreign files."""
    out: List[Tuple[int, str]] = []
    try:
        names = os.listdir(feed_dir)
    except OSError:
        return out
    for fname in names:
        if not (fname.startswith(SEGMENT_PREFIX) and fname.endswith(SEGMENT_SUFFIX)):
            continue
        digits = fname[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)]
        try:
            first = int(digits)
        except ValueError:
            continue
        out.append((first, os.path.join(feed_dir, fname)))
    out.sort()
    return out


def read_segment(path: str) -> Tuple[List[Tuple[int, int, bytes]], int, bool]:
    """Decode one segment file.

    Returns ``(records, valid_bytes, torn)`` where ``records`` is
    ``[(wal_seq, opcode, payload)]`` in file order, ``valid_bytes`` is
    the byte offset of the end of the last intact record, and ``torn``
    is True when the file ends in a short header, short body, or a
    CRC/length mismatch — everything from ``valid_bytes`` on is garbage
    a crashed writer left behind and must be truncated, never replayed.
    """
    records: List[Tuple[int, int, bytes]] = []
    valid = 0
    torn = False
    with open(path, "rb") as f:
        data = f.read()
    size = len(data)
    offset = 0
    while offset < size:
        if offset + _HEADER.size > size:
            torn = True  # short header: the write died mid-record
            break
        body_len, crc = _HEADER.unpack_from(data, offset)
        body_start = offset + _HEADER.size
        body_end = body_start + body_len
        if body_len < _BODY_PREFIX.size or body_end > size:
            torn = True  # short body / absurd length
            break
        body = data[body_start:body_end]
        if zlib.crc32(body) & 0xFFFFFFFF != crc:
            torn = True  # flipped byte(s): CRC mismatch
            break
        seq, opcode = _BODY_PREFIX.unpack_from(body, 0)
        records.append((seq, opcode, body[_BODY_PREFIX.size:]))
        offset = body_end
        valid = offset
    return records, valid, torn


class SegmentWriter:
    """One feed's WAL appender: active segment file + fsync policy.

    All mutable state is guarded by the ``durability.wal`` named lock
    (nested under ``ingest.feed`` on the append path; the group-commit
    flusher thread takes it alone).  Metric fan-out never happens under
    it — callers pass an ``events`` list and emit after their locks
    release (the PR 9 gate-lock lesson).
    """

    def __init__(
        self,
        feed_name: str,
        feed_dir: str,
        next_seq: int,
        policy: str,
        segment_bytes: int,
        reclaim: Callable[[List[Tuple[str, int]]], int],
    ) -> None:
        from modin_tpu.durability import _note_alloc

        _note_alloc()
        self.feed_name = feed_name
        self.feed_dir = feed_dir
        self.policy = policy
        self.segment_bytes = int(segment_bytes)
        self.next_seq = int(next_seq)
        self.degraded = False
        self._reclaim = reclaim
        self._lock = named_lock("durability.wal")
        self._fh: Optional[Any] = None
        self._fh_path: Optional[str] = None
        self._fh_bytes = 0
        self._dirty = False  # unsynced bytes (GroupCommit)

    # -- segment lifecycle (callers hold self._lock) -------------------- #

    def _open_segment_locked(self, first_seq: int) -> None:
        path = segment_path(self.feed_dir, first_seq)
        fh = open(path, "ab", buffering=0)
        self._fh = fh
        self._fh_path = path
        self._fh_bytes = fh.tell()

    def adopt_segment(self, first_seq: int) -> None:
        """Resume appending to an existing (recovered, possibly
        truncated) segment file."""
        with self._lock:
            self._open_segment_locked(first_seq)

    def _close_fh_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
        self._fh = None
        self._fh_path = None
        self._fh_bytes = 0
        self._dirty = False

    # -- the append path ------------------------------------------------ #

    def append(
        self, opcode: int, payload: bytes, events: List[Tuple[str, int]]
    ) -> Optional[int]:
        """Append one record; returns its wal_seq, or None when the feed
        is (or just became) degraded.  Raises
        :class:`~modin_tpu.durability.errors.DurabilityError` only for
        ENOSPC that a reclaim pass could not cure — the one refusal."""
        with self._lock:
            if self.degraded:
                return None
            seq = self.next_seq
            body = _BODY_PREFIX.pack(seq, opcode) + payload
            record = _HEADER.pack(len(body), zlib.crc32(body) & 0xFFFFFFFF) + body
            if (
                self._fh is not None
                and self._fh_bytes + len(record) > self.segment_bytes
                and self._fh_bytes > 0
            ):
                self._close_fh_locked()
                events.append(("wal.segment.roll", 1))
            if self._fh is None and not self._open_with_reclaim_locked(
                seq, events
            ):
                return None
            self._write_record_locked(record, events)
            if self.degraded:
                return None
            self.next_seq = seq + 1
            if self.policy == "PerBatch":
                self._fsync_locked(events)
            elif self.policy == "GroupCommit":
                self._dirty = True
            events.append(("wal.append", 1))
            events.append(("wal.append.bytes", len(record)))
            return seq

    def _open_with_reclaim_locked(
        self, first_seq: int, events: List[Tuple[str, int]]
    ) -> bool:
        """Open a fresh segment, reclaiming once on ENOSPC.  Returns True
        when a segment is open; False means the writer degraded (EIO
        class).  Exhausted ENOSPC raises the typed refusal."""
        try:
            self._open_segment_locked(first_seq)
            return True
        except OSError as err:
            if err.errno != errno.ENOSPC:
                self._degrade_locked(events)
                return False
        events.append(("wal.enospc.reclaim", 1))
        self._reclaim(events)
        try:
            self._open_segment_locked(first_seq)
            return True
        except OSError as err:
            if err.errno == errno.ENOSPC:
                raise DurabilityError(
                    self.feed_name,
                    "enospc",
                    "could not open a WAL segment after reclaim; batch "
                    "refused before any in-memory mutation",
                )
            self._degrade_locked(events)
            return False

    def _write_record_locked(
        self, record: bytes, events: List[Tuple[str, int]]
    ) -> None:
        for attempt in (0, 1):
            try:
                torn_n = disk_op("wal.write")
                if torn_n is not None:
                    # injected torn write: a prefix lands, the process dies
                    # — the genuine crash shape the recovery tests replay
                    self._fh.write(record[: max(0, int(torn_n))])
                    os.fsync(self._fh.fileno())
                    os.kill(os.getpid(), signal.SIGKILL)
                self._fh.write(record)
                self._fh_bytes += len(record)
                return
            except OSError as err:
                if err.errno == errno.ENOSPC and attempt == 0:
                    # retention-driven reclaim: drop checkpoint-covered
                    # segments + stale checkpoints, then retry once
                    events.append(("wal.enospc.reclaim", 1))
                    self._reclaim(events)
                    continue
                if err.errno == errno.ENOSPC:
                    raise DurabilityError(
                        self.feed_name,
                        "enospc",
                        "WAL write hit ENOSPC and reclaim freed nothing; "
                        "batch refused before any in-memory mutation",
                    )
                # EIO-class: trip the breaker, keep serving memory-only
                self._degrade_locked(events)
                return

    def _fsync_locked(self, events: List[Tuple[str, int]]) -> None:
        try:
            disk_op("wal.fsync")
            os.fsync(self._fh.fileno())
            self._dirty = False
            events.append(("wal.fsync", 1))
        except OSError:
            # an fsync that fails is durability already lost: degrade
            self._degrade_locked(events)

    def _degrade_locked(self, events: List[Tuple[str, int]]) -> None:
        if not self.degraded:
            self.degraded = True
            events.append(("wal.degraded", 1))
        self._close_fh_locked()

    # -- group-commit flusher ticks ------------------------------------- #

    def flush_if_dirty(self, events: List[Tuple[str, int]]) -> None:
        with self._lock:
            if self._dirty and not self.degraded and self._fh is not None:
                self._fsync_locked(events)

    def close(self) -> None:
        events: List[Tuple[str, int]] = []
        with self._lock:
            if self._dirty and not self.degraded and self._fh is not None:
                self._fsync_locked(events)
            self._close_fh_locked()

    def active_path(self) -> Optional[str]:
        with self._lock:
            return self._fh_path
