"""Log-file setup and the memory/TPU-memory profiling sampler thread.

Reference design: /root/reference/modin/logging/config.py:112-220 — a rotating
job-scoped trace log plus a daemon thread sampling process RSS.  The TPU build
additionally samples live device memory from jax when available.
"""

from __future__ import annotations

import datetime as dt
import logging
import logging.handlers
import pathlib
import platform
import threading
import time
import uuid

import pandas
import numpy

import modin_tpu
from modin_tpu.concurrency import named_lock
from modin_tpu.config import LogFileSize, LogMemoryInterval, LogMode

__LOGGER_CONFIGURED__: bool = False

# configure_logging claims idempotence; without the lock two threads racing
# through get_logger's "not configured yet" check would both configure —
# duplicate handlers on the trace logger AND two daemon memory-sampler
# threads.  The handle to the (single) sampler thread is kept for
# introspection and tests.
_configure_lock = named_lock("logging.configure")
_mem_sampler: "threading.Thread | None" = None


class ModinFormatter(logging.Formatter):
    """Microsecond-resolution UTC timestamps."""

    def formatTime(self, record, datefmt=None):
        ct = dt.datetime.fromtimestamp(record.created, dt.timezone.utc)
        if datefmt:
            return ct.strftime(datefmt)
        return ct.strftime("%Y-%m-%d %H:%M:%S.%f")


def bytes_int_to_str(num_bytes: int, suffix: str = "B") -> str:
    factor = 1000
    for unit in ["", "K", "M", "G", "T", "P"]:
        if num_bytes < factor:
            return f"{num_bytes:.2f}{unit}{suffix}"
        num_bytes /= factor
    return f"{num_bytes * factor:.2f}P{suffix}"


def _create_logger(
    namespace: str, job_id: str, log_name: str, log_level: int
) -> logging.Logger:
    logger = logging.getLogger(namespace)
    logdir = pathlib.Path(".modin_tpu") / "logs" / f"job_{job_id}"
    logdir.mkdir(parents=True, exist_ok=True)
    log_filename = logdir / f"{log_name}.log"
    handler = logging.handlers.RotatingFileHandler(
        filename=log_filename,
        backupCount=10,
        maxBytes=LogFileSize.get() * int(1e6),
    )
    handler.setFormatter(
        ModinFormatter(fmt="%(process)d, %(thread)d, %(asctime)s, %(message)s")
    )
    logger.addHandler(handler)
    logger.setLevel(log_level)
    return logger


def configure_logging() -> None:
    """Create the trace logger and start the memory sampler (idempotent:
    concurrent first calls configure exactly once, under the module lock)."""
    global __LOGGER_CONFIGURED__, _mem_sampler
    with _configure_lock:
        if __LOGGER_CONFIGURED__:
            return
        job_id = uuid.uuid4().hex
        log_filename = f"trace__{platform.node()}"

        log_level = (
            logging.INFO if LogMode.get() == "Enable_Api_Only" else logging.DEBUG
        )
        logger = _create_logger("modin_tpu.logger", job_id, log_filename, log_level)

        logger.info(f"OS Version: {platform.platform()}")
        logger.info(f"Python Version: {platform.python_version()}")
        logger.info(f"Modin-TPU Version: {modin_tpu.__version__}")
        logger.info(f"Pandas Version: {pandas.__version__}")
        logger.info(f"Numpy Version: {numpy.__version__}")
        try:
            import jax

            logger.info(f"JAX Version: {jax.__version__}")
            logger.info(f"Devices: {[str(d) for d in jax.devices()]}")
        except Exception:
            pass

        if LogMode.get() != "Enable_Api_Only":
            from modin_tpu.observability import meters as graftmeter
            from modin_tpu.observability import spans as graftscope

            mem_sleep = LogMemoryInterval.get()
            mem = _create_logger(
                "modin_tpu_memory.logger", job_id, "memory", logging.DEBUG
            )
            _mem_sampler = threading.Thread(
                target=memory_thread,
                args=[
                    mem,
                    mem_sleep,
                    graftscope.snapshot_stack(),
                    graftmeter.snapshot_scopes(),
                ],
                daemon=True,
                name="modin-tpu-memory-sampler",
            )
            _mem_sampler.start()

        __LOGGER_CONFIGURED__ = True


def memory_thread(
    logger: logging.Logger,
    sleep_time: int,
    span_stack=None,
    scopes=None,
) -> None:
    """Sample host RSS and (if available) device HBM usage forever."""
    from modin_tpu.observability import meters as graftmeter
    from modin_tpu.observability import spans as graftscope

    # configure-once service thread: adopt the configuring thread's
    # observability context (empty outside a query; cheap no-op either way)
    graftscope.seed_thread(span_stack)
    graftmeter.seed_thread_scopes(scopes)
    while True:
        rss = _process_rss_bytes()
        if rss is not None:
            logger.info(f"Host Memory RSS: {bytes_int_to_str(rss)}")
        try:
            import jax

            for d in jax.local_devices():
                stats = getattr(d, "memory_stats", lambda: None)()
                if stats and "bytes_in_use" in stats:
                    logger.info(
                        f"Device {d.id} HBM in use: "
                        f"{bytes_int_to_str(stats['bytes_in_use'])}"
                    )
        except Exception:
            pass
        time.sleep(sleep_time)


def _process_rss_bytes():
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        import resource

        return pages * resource.getpagesize()
    except Exception:
        return None


def get_logger(namespace: str = "modin_tpu.logger") -> logging.Logger:
    """Get the configured trace logger, configuring on first use."""
    if not __LOGGER_CONFIGURED__ and LogMode.get() != "Disable":
        configure_logging()
    return logging.getLogger(namespace)
