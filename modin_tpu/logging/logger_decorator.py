"""``enable_logging`` — the START/STOP trace + metrics decorator.

Reference design: /root/reference/modin/logging/logger_decorator.py:55-69 — every
significant method logs ``START::<layer>::<name>`` / ``STOP::…`` when LogMode is
enabled, and API-layer calls emit timing metrics.
"""

from __future__ import annotations

import re
import time
from functools import wraps
from types import FunctionType, MethodType
from typing import Any, Callable, Optional, Union

from modin_tpu.config import LogMode, MetricsMode
from modin_tpu.logging.config import get_logger
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import spans as graftscope

_MODIN_LOGGER_NOWRAP = "__modin_logging_nowrap__"


def disable_logging(func: Callable) -> Callable:
    """Mark a function to never be wrapped by ``enable_logging``."""
    setattr(func, _MODIN_LOGGER_NOWRAP, True)
    return func


def enable_logging(
    modin_layer: Union[str, Callable, classmethod, staticmethod] = "PANDAS-API",
    name: Optional[str] = None,
    log_level: str = "info",
) -> Callable:
    """Wrap a callable with START/STOP trace logging and timing metrics.

    Usable both as ``@enable_logging`` and ``@enable_logging("LAYER")``.
    """
    if isinstance(modin_layer, (FunctionType, MethodType, classmethod, staticmethod)):
        return enable_logging()(modin_layer)

    def decorator(obj: Any) -> Any:
        if isinstance(obj, classmethod):
            return classmethod(decorator(obj.__func__))
        if isinstance(obj, staticmethod):
            return staticmethod(decorator(obj.__func__))
        if isinstance(obj, type):
            seen: dict = {}
            for attr_name, attr_value in vars(obj).items():
                if isinstance(
                    attr_value, (FunctionType, MethodType, classmethod, staticmethod)
                ) and not hasattr(attr_value, _MODIN_LOGGER_NOWRAP):
                    try:
                        wrapped = seen.setdefault(
                            attr_value,
                            enable_logging(modin_layer, f"{obj.__name__}.{attr_name}")(
                                attr_value
                            ),
                        )
                        setattr(obj, attr_name, wrapped)
                    except (TypeError, AttributeError):
                        pass
            return obj

        assert isinstance(modin_layer, str), "modin_layer is somehow not a string!"
        log_name = name or getattr(obj, "__qualname__", repr(obj))
        log_name = re.sub(r"[^a-zA-Z0-9\-_\.]", "_", log_name)
        full_name = f"{modin_layer}::{log_name}"
        is_api_layer = modin_layer.upper() in graftscope.API_LAYERS

        @wraps(obj)
        def run_and_log(*args: Any, **kwargs: Any) -> Any:
            mode = LogMode.get()
            metrics_on = MetricsMode.get() == "Enable" and is_api_layer
            if is_api_layer:
                from modin_tpu.config import ProgressBar

                if ProgressBar.get():
                    from modin_tpu.core.execution.progress import call_progress_bar

                    with call_progress_bar(log_name):
                        return _run_inner((mode, metrics_on), *args, **kwargs)
            return _run_inner((mode, metrics_on), *args, **kwargs)

        # state rides in ONE private positional: spreading it as named
        # positionals collided with wrapped calls whose own kwargs include
        # e.g. ``mode`` (pandas read_hdf/to_hdf/to_csv all have one)
        def _run_inner(_log_state: tuple, *args: Any, **kwargs: Any) -> Any:
            # graftscope seam: independent of LogMode — one module-attribute
            # check when tracing is off, a nested layer-tagged span when on
            if not graftscope.TRACE_ON:
                return _run_logged(_log_state, *args, **kwargs)
            with graftscope.layer_span(log_name, modin_layer):
                return _run_logged(_log_state, *args, **kwargs)

        def _run_logged(_log_state: tuple, *args: Any, **kwargs: Any) -> Any:
            mode, metrics_on = _log_state
            if mode == "Disable" and not metrics_on:
                return obj(*args, **kwargs)
            if mode == "Enable_Api_Only" and not is_api_layer and not metrics_on:
                return obj(*args, **kwargs)

            logger = get_logger() if mode != "Disable" else None
            if logger is not None and not (
                mode == "Enable_Api_Only" and not is_api_layer
            ):
                getattr(logger, log_level)(f"START::{full_name}")
            start = time.perf_counter()
            try:
                result = obj(*args, **kwargs)
            except BaseException as err:
                if logger is not None:
                    get_logger("modin_tpu.logger.errors").exception(
                        f"STOP::{full_name}", exc_info=err
                    )
                raise
            finally:
                elapsed = time.perf_counter() - start
                if metrics_on:
                    emit_metric(
                        f"pandas-api.{log_name.lower().replace('.', '_', 1)}", elapsed
                    )
            if logger is not None and not (
                mode == "Enable_Api_Only" and not is_api_layer
            ):
                getattr(logger, log_level)(f"STOP::{full_name}")
            return result

        setattr(run_and_log, _MODIN_LOGGER_NOWRAP, True)
        return run_and_log

    return decorator
