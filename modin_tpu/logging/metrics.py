"""API timing metrics: named-handler fan-out, isolated from handler failures.

Reference design: /root/reference/modin/logging/metrics.py:33-70.
"""

from __future__ import annotations

import re
from typing import Callable, Union

from modin_tpu.config import MetricsMode

_metric_handlers: list = []
_metric_name_pattern = re.compile(r"^[a-zA-Z0-9\-_\.]+$")


def emit_metric(name: str, value: Union[int, float]) -> None:
    """Send ``modin_tpu.<name> = value`` to every registered handler."""
    if MetricsMode.get() == "Disable":
        return
    if not _metric_name_pattern.fullmatch(name):
        raise KeyError(f"Metrics name is not in metric-name dot format, e.g. a.b.c : {name}")
    for fn in list(_metric_handlers):
        try:
            fn(f"modin_tpu.{name}", value)
        except Exception:
            # a broken handler must never break the API call it instruments
            _metric_handlers.remove(fn)


def add_metric_handler(handler: Callable[[str, Union[int, float]], None]) -> None:
    _metric_handlers.append(handler)


def clear_metric_handler(handler: Callable[[str, Union[int, float]], None]) -> None:
    if handler in _metric_handlers:
        _metric_handlers.remove(handler)
