"""API timing metrics: named-handler fan-out, isolated from handler failures.

Reference design: /root/reference/modin/logging/metrics.py:33-70.

graftmeter (modin_tpu/observability/meters.py) taps the same stream: while
aggregation is active it installs ``_aggregate`` and every emitted metric is
also folded into the in-process counter/gauge/histogram registry and the
per-query ``QueryStats`` scopes.  While it is off (the default) the only
cost here is one module-attribute read per call.
"""

from __future__ import annotations

import re
from typing import Callable, Optional, Union

from modin_tpu.config import MetricsMode

_metric_handlers: list = []
_metric_name_pattern = re.compile(r"^[a-zA-Z0-9\-_\.]+$")

#: graftmeter aggregation hook.  None while aggregation is off; set to
#: ``meters._dispatch_metric`` by modin_tpu/observability/meters.py whenever
#: ``MODIN_TPU_METERS`` is enabled or a ``query_stats()`` scope is active.
#: emit_metric reads it once per call — the zero-overhead-when-off contract.
_aggregate: Optional[Callable[[str, Union[int, float]], None]] = None

#: Registry of every metric family this package emits: (name pattern,
#: meter kind, what it counts).  ``*`` stands for a runtime-interpolated
#: segment (an engine op, a breaker family, a failure kind).  The **kind**
#: declares how graftmeter aggregates the family — ``counter`` (monotonic
#: sum), ``gauge`` (last value + min/max), or ``histogram`` (fixed buckets
#: declared in observability/meters.py:HISTOGRAM_BUCKETS, exposing
#: p50/p95/p99).  graftlint's REGISTRY-DRIFT rule cross-checks all of it
#: both ways — an ``emit_metric`` name matching no pattern, a pattern with
#: no live emit site, a missing/invalid kind, or a histogram without (or a
#: bucket spec without) its registry entry fails the lint — and requires
#: each family's stable prefix to appear in docs/ (see
#: docs/configuration.md).
METRICS = (
    (
        "resilience.engine.*.*",
        "counter",
        "engine-seam outcomes per op: oom / device_lost / transient / "
        "watchdog_timeout classifications and retry attempts",
    ),
    (
        "resilience.watchdog.*.timeout",
        "counter",
        "materialize/wait attempts killed by the wall-clock watchdog",
    ),
    (
        "resilience.breaker.*.*",
        "counter",
        "circuit-breaker lifecycle per device-path family: state "
        "transitions (open/half_open/closed), strikes, latency-budget "
        "violations (slow), and open-breaker short_circuits",
    ),
    (
        "resilience.fallback.*.*",
        "counter",
        "device failures converted to pandas fallbacks, per family and "
        "failure kind",
    ),
    (
        "resilience.shuffle.slack_retry",
        "counter",
        "range_shuffle capacity overflows retried with doubled slack",
    ),
    (
        "resilience.shuffle.skew_fallback",
        "counter",
        "range_shuffle giving up on pathologically skewed keys "
        "(ShuffleSkewError -> non-shuffle fallback)",
    ),
    (
        "engine.dispatch",
        "counter",
        "successful engine-seam deploys (device dispatches); emitted while "
        "graftmeter accounting is active (meters on or a QueryStats scope)",
    ),
    (
        "engine.compile",
        "counter",
        "XLA backend compiles observed by the jax.monitoring listener "
        "while graftmeter accounting is active",
    ),
    (
        "engine.compile_s",
        "counter",
        "XLA backend compile wall seconds (same gating as engine.compile)",
    ),
    (
        "engine.cost.flops",
        "counter",
        "XLA-estimated floating-point operations per dispatched program "
        "(graftcost static capture; compiles capture fresh, cache hits "
        "re-bill the memoized estimate)",
    ),
    (
        "engine.cost.bytes",
        "counter",
        "XLA-estimated bytes accessed (HBM traffic) per dispatched "
        "program, same capture/re-bill gating as engine.cost.flops",
    ),
    (
        "engine.cost.transcendentals",
        "counter",
        "XLA-estimated transcendental-function evaluations per dispatched "
        "program (emitted only when nonzero)",
    ),
    (
        "engine.cost.peak_bytes",
        "gauge",
        "memory_analysis peak bytes of the dispatched executable "
        "(argument+output+temp fallback when the backend reports no "
        "explicit peak; MODIN_TPU_COST_CAPTURE=Full only)",
    ),
    (
        "engine.cost.padded_bytes",
        "counter",
        "physical bytes of padded device allocations observed at the "
        "padding sites (shard-multiple, pow2 histogram bins, groupby "
        "output buckets, sort sentinels)",
    ),
    (
        "engine.cost.padding_waste_bytes",
        "counter",
        "the pad share of engine.cost.padded_bytes: physical minus "
        "logical bytes — arithmetic/traffic spent on rows no one reads",
    ),
    (
        "engine.cost.collective_bytes",
        "counter",
        "payload bytes moved through interconnect collectives "
        "(all_to_all/psum) at the instrumented sites — the cross-device "
        "traffic term of the graftmesh router's sharded-vs-local crossover",
    ),
    (
        "io.read.bytes",
        "histogram",
        "bytes parsed per FileDispatcher read (source file size, "
        "best-effort; emitted while graftmeter accounting is active)",
    ),
    (
        "concurrency.lockdep.violation",
        "counter",
        "lock-order violations the runtime lockdep validator detected "
        "(MODIN_TPU_LOCKDEP=1): self-deadlock, same-name instance pair, "
        "declared-order contradiction, or observed ABBA inversion; each "
        "also flight-dumps its witness pair",
    ),
    (
        "recovery.device_lost",
        "counter",
        "device-lost events entering the graftguard lineage-recovery "
        "manager (engine-seam terminal DeviceLost or a breaker opening "
        "on one)",
    ),
    (
        "recovery.reseat.*",
        "counter",
        "device columns re-seated from lineage, per provenance kind "
        "(host / io / op), plus the graftmesh single-shard leg (shard: "
        "only the lost shard's slice was re-uploaded, the live shards' "
        "buffers were kept)",
    ),
    (
        "recovery.unrecoverable",
        "counter",
        "live device columns whose lineage could not reproduce their "
        "buffer during a recovery pass",
    ),
    (
        "recovery.checkpoint_cut",
        "counter",
        "op-replay lineage chains cut by an automatic host checkpoint at "
        "MODIN_TPU_LINEAGE_MAX_DEPTH",
    ),
    (
        "recovery.retry.*",
        "counter",
        "engine-seam attempts retried after a recovery action: "
        "device_lost (lineage re-seat), oom (evict-then-retry), or rebind "
        "(deploy re-dispatched over rebuilt argument buffers)",
    ),
    (
        "memory.device.spill",
        "counter",
        "device columns spilled to host by admission control or the OOM "
        "evict-then-retry leg",
    ),
    (
        "memory.device.spill_bytes",
        "counter",
        "device bytes freed by spills (exact host copies retained)",
    ),
    (
        "memory.device.restore",
        "counter",
        "spilled columns transparently re-seated on device on access",
    ),
    (
        "memory.device.resident_bytes",
        "gauge",
        "device-resident bytes tracked by the device ledger, observed "
        "after each spill pass",
    ),
    (
        "memory.host.cache_bytes",
        "gauge",
        "host bytes pinned by device-column caches, observed after each "
        "spill pass",
    ),
    (
        "memory.device.shard_resident_bytes",
        "gauge",
        "largest per-shard share of device-resident bytes (the binding "
        "constraint on a mesh: one shard's HBM fills first), observed "
        "after each spill pass",
    ),
    (
        "router.*.*",
        "counter",
        "kernel-router decisions: device vs host choice counts per "
        "sort-shaped op family (median/quantile/nunique/mode), and "
        "graftmesh local-vs-sharded layout choices per collective-eligible "
        "op (spmd_sort / spmd_merge)",
    ),
    (
        "router.calibrate",
        "counter",
        "one-shot kernel-router micro-benchmark calibrations (cold "
        "CacheDir for this substrate)",
    ),
    (
        "sortcache.*",
        "counter",
        "sorted-representation cache lifecycle: build (one shared sort "
        "paid), hit (a later sort-shaped op consumed it), invalidate "
        "(buffer mutation / spill / re-seat dropped it), spill (the "
        "device-memory ledger reclaimed it under pressure)",
    ),
    (
        "view.hit",
        "counter",
        "graftview derived-artifact registry answers: a whole reduction "
        "result / sort-shaped answer / groupby table served without any "
        "device work, shared across every query on the same buffer epoch",
    ),
    (
        "view.miss",
        "counter",
        "graftview registry consults that found no usable artifact (the "
        "op computes from scratch and stores one)",
    ),
    (
        "view.build",
        "counter",
        "graftview artifacts cached after a from-scratch computation",
    ),
    (
        "view.fold",
        "counter",
        "graftview incremental maintenance: an artifact absorbed an "
        "appended tail (algebraic scalar combine, groupby partial-table "
        "combine, or dictionary code-table extension) instead of a full "
        "recompute — only the delta was dispatched",
    ),
    (
        "view.invalidate.*",
        "counter",
        "graftview artifacts dropped, by reason: buffer (mutation / spill "
        "/ re-seat / donation), device_epoch (recovery pass), "
        "mesh_reshape, not_incremental (an append reached an artifact "
        "with no fold rule — dropped once its owning column is gone; a "
        "live parent keeps its warm answer and the child just misses), "
        "pressure (the ledger reclaimed a cold column's caches), dead",
    ),
    (
        "view.evict",
        "counter",
        "graftview artifacts evicted coldest-first past "
        "MODIN_TPU_VIEWS_MAX_ENTRIES / MODIN_TPU_VIEWS_HOST_BUDGET",
    ),
    (
        "view.spill",
        "counter",
        "graftview device-payload artifacts dropped by the device-memory "
        "ledger under pressure (before any real column spills)",
    ),
    (
        "plan.defer.scan",
        "counter",
        "reads deferred into graftplan Scan-rooted logical plans instead "
        "of parsing at the call site",
    ),
    (
        "plan.optimize.passes",
        "histogram",
        "rewrite passes run to fixpoint (bounded by "
        "MODIN_TPU_PLAN_MAX_PASSES) per plan materialization",
    ),
    (
        "plan.rule.*",
        "counter",
        "graftplan rewrite-rule applications per rule (pushdown-filter / "
        "cse / prune-columns / pushdown-project-into-scan / "
        "fuse-map-reduce)",
    ),
    (
        "plan.rule_rejected.*",
        "counter",
        "graftplan rewrite applications rejected by graftopt's cost gate "
        "per rule (modeled cost rose beyond the tolerance)",
    ),
    (
        "opt.choose",
        "counter",
        "graftopt joint strategy passes (one per plan materialization "
        "under MODIN_TPU_OPT=Auto; re-plans count again)",
    ),
    (
        "opt.replan.*",
        "counter",
        "graftopt mid-query re-plans per trigger (wall_divergence / "
        "ledger_pressure / compile_storm): the remaining plan segment was "
        "re-optimized against live evidence",
    ),
    (
        "plan.lower.nodes",
        "histogram",
        "distinct plan nodes lowered per materialization (shared subtrees "
        "count once — the one-scan guarantee is this number)",
    ),
    (
        "plan.scan.pruned_columns",
        "counter",
        "columns never parsed because projection pushdown narrowed the "
        "reader (per physical pruned read; scans served from a prior "
        "materialization's cache emit nothing)",
    ),
    (
        "plan.scan.cache_hit",
        "counter",
        "scans served from a prior materialization's read cache instead "
        "of re-parsing the source",
    ),
    (
        "plan.scan.cache_evict",
        "counter",
        "materialized-scan cache entries dropped because the origin's "
        "measured cached bytes crossed MODIN_TPU_PLAN_SCAN_CACHE_BYTES "
        "(coldest projection first)",
    ),
    (
        "stream.window.count",
        "counter",
        "resident windows completed by the graftstream out-of-core "
        "executor (scan window loops and external-sort windows)",
    ),
    (
        "stream.window.rows",
        "counter",
        "rows processed per streaming window (parse or sort slice)",
    ),
    (
        "stream.window.bytes",
        "counter",
        "source bytes parsed per streaming scan window (record-aligned "
        "byte range)",
    ),
    (
        "stream.window.replay",
        "counter",
        "windows replayed after a terminal mid-stream device failure: one "
        "window's byte range re-parsed and re-run, never the dataset",
    ),
    (
        "stream.prefetch.wait_s",
        "counter",
        "seconds the consuming thread waited on the prefetch worker per "
        "window (0 when the parse fully hid behind the previous kernel)",
    ),
    (
        "stream.prefetch.overlap_s",
        "counter",
        "seconds of window parse+deploy wall hidden behind the previous "
        "window's kernel (parse wall minus consumer wait, floored at 0) — "
        "the pipelining win the oocore bench measures",
    ),
    (
        "stream.degrade",
        "counter",
        "streaming groupbys degraded to the resident (range_shuffle-"
        "capable) path because the partial-state table crossed "
        "MODIN_TPU_STREAM_MAX_GROUPS distinct groups",
    ),
    (
        "stream.spill.run_bytes",
        "counter",
        "host bytes spilled as sorted runs by the external sort (merge "
        "keys + row ids per window)",
    ),
    (
        "fusion.cache.evict",
        "counter",
        "fused-executable LRU evictions under MODIN_TPU_FUSED_CACHE_SIZE "
        "(ops/lazy.py)",
    ),
    (
        "fusion.cache.hit",
        "counter",
        "fused-executable cache hits (a fused forest re-dispatched without "
        "re-jitting; emitted while graftmeter accounting is active)",
    ),
    (
        "fuse.dispatch",
        "counter",
        "graftfuse whole-plan dispatches: one compiled program covering "
        "the entire post-scan segment (filter/map/project chain plus its "
        "reduce or groupby tail) instead of one dispatch per stage",
    ),
    (
        "fuse.donated",
        "counter",
        "input columns whose buffers rode in donated jit positions of a "
        "fused program (the device ledger proved no other live consumer; "
        "the column restores via lineage on next access)",
    ),
    (
        "fuse.donated_bytes",
        "counter",
        "device bytes released by graftfuse buffer donation (freed by XLA "
        "at the dispatch instead of surviving to the next GC pass; reused "
        "in place where an output shape aliases an input)",
    ),
    (
        "fuse.donated_restore",
        "counter",
        "donated columns rebuilt via lineage (exact host copy) on their "
        "first post-donation device access — the use-after-donate guard "
        "doing its job",
    ),
    (
        "fuse.decline",
        "counter",
        "fused-eligible segments that fell back to the staged lowering "
        "mid-flight (unsupported tail kwargs, zero kept rows, key range "
        "over the group-bucket cap)",
    ),
    (
        "fuse.bucket.quantized",
        "counter",
        "scan uploads whose padding was quantized to a recompile-storm "
        "bucket (adaptive padding chosen from the compile ledger's "
        "recompile_storms feedback; pad rows per upload as the value)",
    ),
    (
        "pandas-api.*",
        "histogram",
        "wall-clock seconds per public pandas-API call (logging layer)",
    ),
    (
        "trace.flight_dump",
        "counter",
        "graftscope flight-recorder ring dumps written on a breaker-open "
        "or terminal device failure",
    ),
    (
        "serving.admit",
        "counter",
        "queries admitted by the graftgate admission gate (serving/)",
    ),
    (
        "serving.queued",
        "counter",
        "admissions that waited in the bounded queue before a slot opened",
    ),
    (
        "serving.queue_wait_s",
        "histogram",
        "seconds an admitted query spent in the admission queue",
    ),
    (
        "serving.shed",
        "counter",
        "queries rejected with a typed QueryRejected (queue_full / "
        "tenant throttled / tenant unhealthy) before any work ran",
    ),
    (
        "serving.deadline_exceeded",
        "counter",
        "queries aborted by their latency budget (typed DeadlineExceeded "
        "at a seam boundary or while queued)",
    ),
    (
        "serving.degraded",
        "counter",
        "admitted queries routed to the host/pandas path because a "
        "device-path breaker was open or the device ledger was past the "
        "degraded high-water fraction",
    ),
    (
        "serving.degraded.fallback",
        "counter",
        "device-path families short-circuited to the pandas fallback "
        "because the running query was admitted in degraded mode",
    ),
    (
        "serving.query_wall_s",
        "histogram",
        "end-to-end wall seconds per submitted query (admission to result)",
    ),
    (
        "serving.tenant.*.*",
        "counter",
        "per-tenant serving outcomes: admit, complete, degraded, deadline, "
        "device_failure, and the shed reasons (queue_full / throttled / "
        "unhealthy)",
    ),
    (
        "watch.sampler.died",
        "counter",
        "graftwatch sampler-thread crashes: the telemetry service degraded "
        "itself to disabled instead of taking queries down",
    ),
    (
        "watch.trip.*",
        "counter",
        "graftwatch anomaly tripwires fired, per rule (latency_shift / "
        "recompile_storm / spill_thrash / shed_spike / slo_burn)",
    ),
    (
        "watch.evidence",
        "counter",
        "graftwatch evidence bundles written to MODIN_TPU_TRACE_DIR after "
        "a tripwire fired (rate-limited through the flight recorder's "
        "claim-token window)",
    ),
    (
        "watch.scrape",
        "counter",
        "HTTP requests served by the graftwatch live exporter "
        "(/metrics, /statusz, /debug/queries)",
    ),
    (
        "view.export",
        "counter",
        "graftview artifacts exported for a respawning fleet replica "
        "(host-state records a survivor hands the coordinator)",
    ),
    (
        "view.ingest",
        "counter",
        "graftview artifacts ingested by a re-warming fleet replica "
        "(warm derived answers restored without recomputation)",
    ),
    (
        "fleet.replica.spawn",
        "counter",
        "graftfleet replica processes spawned (initial fleet start and "
        "every respawn generation)",
    ),
    (
        "fleet.replica.lost",
        "counter",
        "graftfleet replicas declared lost — by process exit, heartbeat "
        "silence with a failed liveness probe, or a dead socket under a "
        "dispatched query",
    ),
    (
        "fleet.replica.heartbeat_miss",
        "counter",
        "graftfleet heartbeat-age trips (~3 intervals silent); each one "
        "triggers a fresh-dial liveness probe before any loss verdict",
    ),
    (
        "fleet.replica.respawned",
        "counter",
        "graftfleet replicas respawned and re-warmed (manifest replay + "
        "graftview artifact ingest) back to routable",
    ),
    (
        "fleet.query.routed",
        "counter",
        "graftfleet queries dispatched to a replica and joined to a typed "
        "outcome",
    ),
    (
        "fleet.query.redispatch",
        "counter",
        "graftfleet in-flight queries re-dispatched to a survivor after "
        "their replica died mid-query (idempotent-by-lineage only)",
    ),
    (
        "fleet.drain.redistributed",
        "counter",
        "graftfleet tenants drained off a lost replica and reassigned "
        "weighted-fair across survivors (value = tenants moved; survivor "
        "typed-shed rate is the backpressure weight)",
    ),
    (
        "fleet.warm.dataset",
        "counter",
        "graftfleet datasets re-warmed from the recovery manifest through "
        "the public readers (io lineage / spans / cost accounting all see "
        "the replay)",
    ),
    (
        "ingest.batch",
        "counter",
        "graftfeed micro-batches admitted (append or the appending half "
        "of an upsert) after schema validation",
    ),
    (
        "ingest.rows",
        "counter",
        "graftfeed rows admitted per micro-batch (value = batch row count)",
    ),
    (
        "ingest.reject",
        "counter",
        "graftfeed micro-batches rejected with a typed IngestRejected "
        "(schema/dtype mismatch, malformed payload, key violation)",
    ),
    (
        "ingest.upsert",
        "counter",
        "graftfeed keyed rows updated in place by an upsert batch (value "
        "= updated row count; each upsert also rebuilds the views)",
    ),
    (
        "ingest.trim.rows",
        "counter",
        "graftfeed rows trimmed off a feed's prefix by retention bounds "
        "(row-count / age); views refold from retained partials",
    ),
    (
        "ingest.fold",
        "counter",
        "graftfeed pending micro-batches folded into every registered "
        "view's running state (value = batches folded in the pass)",
    ),
    (
        "ingest.rebuild",
        "counter",
        "graftfeed exact view rebuilds (value = views rebuilt): upserts "
        "and bootstrap-intersecting trims collapse the partial log to one "
        "bootstrap partial over the retained frame",
    ),
    (
        "ingest.view.refused",
        "counter",
        "graftfeed view registrations refused with a typed "
        "ViewNotIncrementalizable (never silently recomputed)",
    ),
    (
        "ingest.read.served",
        "counter",
        "graftfeed staleness-bounded reads served straight off the "
        "maintained view state (fold lag inside the freshness bound)",
    ),
    (
        "ingest.read.forced_fold",
        "counter",
        "graftfeed reads whose freshness bound forced a synchronous fold "
        "of the pending batches before serving",
    ),
    (
        "view.lag_ms",
        "histogram",
        "fold lag observed at each graftfeed view read (ms): age of the "
        "oldest unfolded batch at serve time (0 after a forced fold)",
    ),
    (
        "view.chain_compact",
        "counter",
        "graftview append-link chains compacted past "
        "MODIN_TPU_VIEWS_MAX_CHAIN (note_append re-anchoring plus lookup "
        "path compression) — keeps micro-batch fold walks O(1)",
    ),
    (
        "structural.append_fastpath",
        "counter",
        "concat_rows micro-batch fast path taken: the small tail was "
        "placed into the grown prefix buffer instead of re-gathering "
        "every row",
    ),
    (
        "wal.append",
        "counter",
        "graftwal records appended (accepted micro-batches + view "
        "registrations) — each lands on disk BEFORE the in-memory mutation",
    ),
    (
        "wal.append.bytes",
        "counter",
        "graftwal bytes appended to segment files (value = record size "
        "including header)",
    ),
    (
        "wal.fsync",
        "counter",
        "graftwal fsync calls issued (per batch under PerBatch, per "
        "flusher tick under GroupCommit)",
    ),
    (
        "wal.segment.roll",
        "counter",
        "graftwal segment files rolled past MODIN_TPU_WAL_SEGMENT_BYTES",
    ),
    (
        "wal.truncate.segments",
        "counter",
        "graftwal segment files deleted (value = files): checkpoint "
        "truncation of fully-covered segments, ENOSPC reclaim, or "
        "unreachable segments past a torn tail",
    ),
    (
        "wal.torn_tail",
        "counter",
        "graftwal torn tails truncated during recovery: the segment ended "
        "in a short header/body or CRC mismatch and everything past the "
        "last intact record was discarded with accounting, never a crash",
    ),
    (
        "wal.degraded",
        "counter",
        "graftwal per-feed breakers tripped into memory-only degraded "
        "mode by an EIO-class write/fsync failure — ingestion keeps "
        "working, durability honestly reports itself lost",
    ),
    (
        "wal.enospc.reclaim",
        "counter",
        "graftwal ENOSPC reclaim passes: checkpoint-covered segments and "
        "stale checkpoints deleted before retrying the refused write",
    ),
    (
        "wal.replay.batches",
        "counter",
        "graftwal records replayed through the ordinary ingest path "
        "during crash recovery (value = records past the checkpoint)",
    ),
    (
        "wal.replay.skipped",
        "counter",
        "graftwal records skipped as already applied during replay "
        "(covered by the checkpoint — the idempotence accounting)",
    ),
    (
        "checkpoint.write",
        "counter",
        "graftwal checkpoints written (temp-file + fsync + atomic rename "
        "of the feed frame plus every view's fold state)",
    ),
    (
        "checkpoint.bytes",
        "counter",
        "graftwal checkpoint payload bytes written (value = serialized "
        "snapshot size)",
    ),
    (
        "checkpoint.load",
        "counter",
        "graftwal checkpoints loaded successfully at recovery",
    ),
    (
        "checkpoint.invalid",
        "counter",
        "graftwal checkpoint files refused at recovery (CRC/unpickle "
        "failure or foreign schema tag) — recovery falls back to the "
        "next-older checkpoint instead of crashing",
    ),
    (
        "recovery.feed",
        "counter",
        "graftwal feed recoveries completed (checkpoint restore + WAL "
        "tail replay, run under the serving gate as a maintenance query)",
    ),
)


def emit_metric(name: str, value: Union[int, float]) -> None:
    """Send ``modin_tpu.<name> = value`` to every registered handler.

    graftmeter aggregation is a separate consumer from the handler fan-out:
    ``MODIN_TPU_METRICS_MODE=Disable`` silences the handlers but does NOT
    turn off an active aggregator (meters on, or a ``query_stats()`` scope)
    — ``explain(analyze=True)`` must account even in a process that muted
    its metric handlers.
    """
    aggregate = _aggregate
    handlers_on = MetricsMode.get() != "Disable"
    if aggregate is None and not handlers_on:
        return
    if not _metric_name_pattern.fullmatch(name):
        raise KeyError(f"Metrics name is not in metric-name dot format, e.g. a.b.c : {name}")
    if aggregate is not None:
        aggregate(name, value)
    if not handlers_on:
        return
    for fn in list(_metric_handlers):
        try:
            fn(f"modin_tpu.{name}", value)
        except Exception:
            # a broken handler must never break the API call it instruments
            _metric_handlers.remove(fn)


def add_metric_handler(handler: Callable[[str, Union[int, float]], None]) -> None:
    _metric_handlers.append(handler)


def clear_metric_handler(handler: Callable[[str, Union[int, float]], None]) -> None:
    if handler in _metric_handlers:
        _metric_handlers.remove(handler)
