"""API timing metrics: named-handler fan-out, isolated from handler failures.

Reference design: /root/reference/modin/logging/metrics.py:33-70.
"""

from __future__ import annotations

import re
from typing import Callable, Union

from modin_tpu.config import MetricsMode

_metric_handlers: list = []
_metric_name_pattern = re.compile(r"^[a-zA-Z0-9\-_\.]+$")

#: Registry of every metric family this package emits (name pattern, what it
#: counts).  ``*`` stands for a runtime-interpolated segment (an engine op,
#: a breaker family, a failure kind).  graftlint's REGISTRY-DRIFT rule
#: cross-checks this both ways — an ``emit_metric`` name matching no pattern,
#: or a pattern with no live emit site, fails the lint — and requires each
#: family's stable prefix to appear in docs/ (see docs/configuration.md).
METRICS = (
    (
        "resilience.engine.*.*",
        "engine-seam outcomes per op: oom / device_lost / transient / "
        "watchdog_timeout classifications and retry attempts",
    ),
    (
        "resilience.watchdog.*.timeout",
        "materialize/wait attempts killed by the wall-clock watchdog",
    ),
    (
        "resilience.breaker.*.*",
        "circuit-breaker lifecycle per device-path family: state "
        "transitions (open/half_open/closed), strikes, latency-budget "
        "violations (slow), and open-breaker short_circuits",
    ),
    (
        "resilience.fallback.*.*",
        "device failures converted to pandas fallbacks, per family and "
        "failure kind",
    ),
    (
        "resilience.shuffle.slack_retry",
        "range_shuffle capacity overflows retried with doubled slack",
    ),
    (
        "resilience.shuffle.skew_fallback",
        "range_shuffle giving up on pathologically skewed keys "
        "(ShuffleSkewError -> non-shuffle fallback)",
    ),
    (
        "recovery.device_lost",
        "device-lost events entering the graftguard lineage-recovery "
        "manager (engine-seam terminal DeviceLost or a breaker opening "
        "on one)",
    ),
    (
        "recovery.reseat.*",
        "device columns re-seated from lineage, per provenance kind "
        "(host / io / op)",
    ),
    (
        "recovery.unrecoverable",
        "live device columns whose lineage could not reproduce their "
        "buffer during a recovery pass",
    ),
    (
        "recovery.checkpoint_cut",
        "op-replay lineage chains cut by an automatic host checkpoint at "
        "MODIN_TPU_LINEAGE_MAX_DEPTH",
    ),
    (
        "recovery.retry.*",
        "engine-seam attempts retried after a recovery action: "
        "device_lost (lineage re-seat), oom (evict-then-retry), or rebind "
        "(deploy re-dispatched over rebuilt argument buffers)",
    ),
    (
        "memory.device.spill",
        "device columns spilled to host by admission control or the OOM "
        "evict-then-retry leg",
    ),
    (
        "memory.device.spill_bytes",
        "device bytes freed by spills (exact host copies retained)",
    ),
    (
        "memory.device.restore",
        "spilled columns transparently re-seated on device on access",
    ),
    (
        "router.*.*",
        "graftsort kernel-router decisions per sort-shaped op family "
        "(median/quantile/nunique/mode): device vs host choice counts",
    ),
    (
        "router.calibrate",
        "one-shot kernel-router micro-benchmark calibrations (cold "
        "CacheDir for this substrate)",
    ),
    (
        "sortcache.*",
        "sorted-representation cache lifecycle: build (one shared sort "
        "paid), hit (a later sort-shaped op consumed it), invalidate "
        "(buffer mutation / spill / re-seat dropped it), spill (the "
        "device-memory ledger reclaimed it under pressure)",
    ),
    (
        "plan.defer.scan",
        "reads deferred into graftplan Scan-rooted logical plans instead "
        "of parsing at the call site",
    ),
    (
        "plan.optimize.passes",
        "rewrite passes run to fixpoint (bounded by "
        "MODIN_TPU_PLAN_MAX_PASSES) per plan materialization",
    ),
    (
        "plan.rule.*",
        "graftplan rewrite-rule applications per rule (pushdown-filter / "
        "cse / prune-columns / pushdown-project-into-scan / "
        "fuse-map-reduce)",
    ),
    (
        "plan.lower.nodes",
        "distinct plan nodes lowered per materialization (shared subtrees "
        "count once — the one-scan guarantee is this number)",
    ),
    (
        "plan.scan.pruned_columns",
        "columns never parsed because projection pushdown narrowed the "
        "reader (per physical pruned read; scans served from a prior "
        "materialization's cache emit nothing)",
    ),
    (
        "fusion.cache.evict",
        "fused-executable LRU evictions under MODIN_TPU_FUSED_CACHE_SIZE "
        "(ops/lazy.py)",
    ),
    (
        "pandas-api.*",
        "wall-clock seconds per public pandas-API call (logging layer)",
    ),
    (
        "trace.flight_dump",
        "graftscope flight-recorder ring dumps written on a breaker-open "
        "or terminal device failure",
    ),
)


def emit_metric(name: str, value: Union[int, float]) -> None:
    """Send ``modin_tpu.<name> = value`` to every registered handler."""
    if MetricsMode.get() == "Disable":
        return
    if not _metric_name_pattern.fullmatch(name):
        raise KeyError(f"Metrics name is not in metric-name dot format, e.g. a.b.c : {name}")
    for fn in list(_metric_handlers):
        try:
            fn(f"modin_tpu.{name}", value)
        except Exception:
            # a broken handler must never break the API call it instruments
            _metric_handlers.remove(fn)


def add_metric_handler(handler: Callable[[str, Union[int, float]], None]) -> None:
    _metric_handlers.append(handler)


def clear_metric_handler(handler: Callable[[str, Union[int, float]], None]) -> None:
    if handler in _metric_handlers:
        _metric_handlers.remove(handler)
