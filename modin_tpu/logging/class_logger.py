"""``ClassLogger`` mixin — auto-wraps all methods of a subclass with tracing.

Reference design: /root/reference/modin/logging/class_logger.py:26.
"""

from __future__ import annotations

from typing import Dict, Optional

from modin_tpu.logging.logger_decorator import enable_logging


class ClassLogger:
    """Ensure all subclass methods are traced under a ``modin_layer`` tag.

    Example::

        class TpuDataframe(ClassLogger, modin_layer="CORE-FRAME"):
            ...
    """

    _modin_logging_layer = "DEFAULT"

    @classmethod
    def __init_subclass__(
        cls,
        modin_layer: Optional[str] = None,
        class_name: Optional[str] = None,
        log_level: str = "info",
        **kwargs: Dict,
    ) -> None:
        super().__init_subclass__(**kwargs)
        modin_layer = modin_layer or cls._modin_logging_layer
        cls._modin_logging_layer = modin_layer
        enable_logging(modin_layer, class_name, log_level)(cls)
