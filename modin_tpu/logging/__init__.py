"""Tracing, metrics and class-level instrumentation.

Reference design: /root/reference/modin/logging/__init__.py.
"""

from modin_tpu.logging.class_logger import ClassLogger  # noqa: F401
from modin_tpu.logging.config import get_logger  # noqa: F401
from modin_tpu.logging.logger_decorator import (  # noqa: F401
    disable_logging,
    enable_logging,
)
from modin_tpu.logging.metrics import (  # noqa: F401
    add_metric_handler,
    clear_metric_handler,
    emit_metric,
)
