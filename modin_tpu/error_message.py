"""Centralized user-facing warnings and defensive assertions.

Reference design: /root/reference/modin/error_message.py:57,83.
"""

from __future__ import annotations

import warnings
from typing import NoReturn


class ErrorMessage:
    printed_default_to_pandas = False
    printed_warnings: set = set()

    @classmethod
    def not_implemented(cls, message: str = "") -> NoReturn:
        if message == "":
            message = "This functionality is not yet available in modin_tpu."
        raise NotImplementedError(message)

    @classmethod
    def single_warning(cls, message: str) -> None:
        message_hash = hash(message)
        if message_hash in cls.printed_warnings:
            return
        warnings.warn(message)
        cls.printed_warnings.add(message_hash)

    @classmethod
    def default_to_pandas(cls, message: str = "", reason: str = "") -> None:
        if message != "":
            message = f"{message} defaulting to in-process pandas implementation."
        else:
            message = "Defaulting to in-process pandas implementation."
        if reason:
            message += f" Reason: {reason}"
        if not cls.printed_default_to_pandas:
            message += (
                "\nThis warning is shown once per session. The operation runs on the "
                "host CPU instead of the TPU; results are identical but unsharded."
            )
            cls.printed_default_to_pandas = True
        warnings.warn(message)

    @classmethod
    def catch_bugs_and_request_email(
        cls, failure_condition: bool, extra_log: str = ""
    ) -> None:
        if failure_condition:
            raise Exception(
                "Internal modin_tpu error — please file an issue with this trace. "
                + extra_log
            )

    @classmethod
    def non_verified_udf(cls) -> None:
        warnings.warn(
            "User-defined function verification is still under development in "
            "modin_tpu. The function provided is not verified."
        )

    @classmethod
    def mismatch_with_pandas(cls, operation: str, message: str) -> None:
        cls.single_warning(
            f"`{operation}` implementation has mismatches with pandas:\n{message}."
        )

    @classmethod
    def missmatch_with_pandas(cls, operation: str, message: str) -> None:
        # Kept for reference-name compatibility (modin/error_message.py misspelling).
        cls.mismatch_with_pandas(operation, message)
