"""graftmeter: in-process metric aggregation + per-query resource accounting.

``emit_metric`` (modin_tpu/logging/metrics.py) has always been fire-and-
forget: values fan out to registered handlers and vanish.  This module is
the measurement layer on top of that stream:

- **Aggregation registry** — every emitted metric folds into a typed meter
  (counter / gauge / fixed-bucket histogram) keyed by its emitted name; the
  kind comes from the family's declaration in the ``METRICS`` registry
  (each entry is ``(pattern, kind, description)``).  ``snapshot()`` returns
  the whole registry as plain dicts (p50/p95/p99 for histograms),
  ``reset()`` clears it; ``observability/exposition.py`` renders a snapshot
  as Prometheus text format or JSON.

- **Per-query accounting** — a :func:`query_stats` scope rolls up, per
  thread, everything a query consumed: wall time, device dispatches, XLA
  compiles (count + seconds, via the compile-ledger listener), bytes parsed
  by FileDispatcher reads, HBM high-water and spill/restore traffic from
  the device ledger, recovery events, and cache hits across the fused /
  sorted-rep / plan-scan caches.  Scopes nest and are thread-isolated: a
  metric emitted on thread A never lands in thread B's open scope.
  ``explain(analyze=True)`` runs a deferred plan inside such a scope and
  annotates every executed plan node with its measured share.

Disabled-mode contract (the default, ``MODIN_TPU_METERS=0`` and no active
query-stats scope): ``emit_metric`` pays one module-attribute read
(``metrics._aggregate`` is None) and the instrumented seams pay one
attribute check of :data:`ACCOUNTING_ON` — no aggregation object is ever
allocated, asserted via :func:`meter_alloc_count` exactly the way
``spans.span_alloc_count()`` asserts the tracing contract.
"""

from __future__ import annotations

import contextlib
import fnmatch
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple, Union

from modin_tpu.concurrency import named_lock

#: Module-level fast path, graftscope-style: True while the aggregation
#: registry (``MODIN_TPU_METERS``) or at least one ``query_stats()`` scope
#: is live.  Instrumented seams (engine dispatch accounting, compile
#: listener, FileDispatcher byte accounting, fused-cache hit accounting)
#: check this ONE attribute before doing anything else.
ACCOUNTING_ON: bool = False

#: True while ``MODIN_TPU_METERS`` is enabled (registry aggregation).
METERS_ON: bool = False

#: Fixed bucket upper bounds for every histogram-kind family declared in
#: ``METRICS`` (modin_tpu/logging/metrics.py).  Keys are the exact registry
#: patterns; graftlint's REGISTRY-DRIFT rule cross-checks this mapping both
#: ways (a histogram family without buckets, or a bucket spec without a
#: histogram family, fails the lint).  Values below the first bound land in
#: the first bucket; values above the last land in the overflow bucket.
HISTOGRAM_BUCKETS: Dict[str, Tuple[float, ...]] = {
    # wall-clock seconds per public pandas-API call
    "pandas-api.*": (
        0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
        0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    ),
    # bytes parsed per FileDispatcher read
    "io.read.bytes": (
        1 << 10, 4 << 10, 16 << 10, 64 << 10, 256 << 10,
        1 << 20, 4 << 20, 16 << 20, 64 << 20, 256 << 20, 1 << 30,
    ),
    # rewrite passes to fixpoint per plan materialization
    "plan.optimize.passes": (1, 2, 3, 4, 6, 8, 12, 16),
    # distinct plan nodes lowered per materialization
    "plan.lower.nodes": (1, 2, 4, 8, 16, 32, 64, 128, 256),
    # fold lag (ms) observed at each graftfeed view read
    "view.lag_ms": (
        0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
        500.0, 1000.0, 2500.0, 5000.0, 10000.0,
    ),
    # seconds an admitted query spent in the admission queue (graftgate)
    "serving.queue_wait_s": (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
        0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    ),
    # end-to-end wall seconds per submitted query (graftgate; the bench's
    # concurrent section reads p50/p99 straight off this family)
    "serving.query_wall_s": (
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    ),
}

VALID_KINDS = ("counter", "gauge", "histogram")

_alloc_count = 0  # meter objects ever constructed (the zero-alloc assertion)

_qs_tls = threading.local()  # .stack: active QueryStats; .dispatches: count

_scope_lock = named_lock("meters.scopes")
_active_scopes = 0

#: every currently-open QueryStats scope, process-wide (insertion order =
#: open order).  Maintained under _scope_lock by query_stats enter/exit;
#: graftwatch's /debug/queries endpoint renders this live.
_live_scopes: Dict[int, "QueryStats"] = {}

_env_enabled = False

#: long-lived registry consumers (graftwatch): registry aggregation is
#: active while ANY consumer holds an acquire, independent of the
#: MODIN_TPU_METERS knob — the watch sampler/exporter need the series to
#: exist without asking the operator to flip a second switch
_registry_consumers = 0


def acquire_registry() -> None:
    """Activate registry aggregation on behalf of a long-lived consumer.

    Balanced by :func:`release_registry`; callers (the graftwatch
    service) must hold at most one acquire per logical consumer."""
    global _registry_consumers
    with _scope_lock:
        _registry_consumers += 1
        _refresh_enabled()


def release_registry() -> None:
    global _registry_consumers
    with _scope_lock:
        _registry_consumers = max(_registry_consumers - 1, 0)
        _refresh_enabled()


def meter_alloc_count() -> int:
    """How many aggregation objects this process has ever constructed.

    The disabled-mode contract is *zero new allocations*; tests snapshot
    this counter around a workload run with meters off.
    """
    return _alloc_count


# ---------------------------------------------------------------------- #
# meter types
# ---------------------------------------------------------------------- #


class Counter:
    """Monotonic sum of emitted values (plus emission count)."""

    __slots__ = ("total", "count")
    kind = "counter"

    def __init__(self) -> None:
        global _alloc_count
        _alloc_count += 1
        self.total = 0.0
        self.count = 0

    def add(self, value: Union[int, float]) -> None:
        self.total += value
        self.count += 1

    def snapshot(self) -> dict:
        total = self.total
        if isinstance(total, float) and total.is_integer():
            total = int(total)
        return {"kind": "counter", "total": total, "count": self.count}


class Gauge:
    """Last emitted value, with min/max/count over the window."""

    __slots__ = ("value", "min", "max", "count")
    kind = "gauge"

    def __init__(self) -> None:
        global _alloc_count
        _alloc_count += 1
        self.value = 0.0
        self.min = None
        self.max = None
        self.count = 0

    def add(self, value: Union[int, float]) -> None:
        self.value = value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        self.count += 1

    def snapshot(self) -> dict:
        return {
            "kind": "gauge",
            "value": self.value,
            "min": self.min,
            "max": self.max,
            "count": self.count,
        }


class Histogram:
    """Fixed-bucket histogram: per-bucket counts + sum/min/max, with
    percentile estimation by linear interpolation inside the bucket."""

    __slots__ = ("bounds", "bucket_counts", "count", "sum", "min", "max")
    kind = "histogram"

    def __init__(self, bounds: Tuple[float, ...]) -> None:
        global _alloc_count
        _alloc_count += 1
        self.bounds = tuple(float(b) for b in bounds)
        self.bucket_counts = [0] * (len(self.bounds) + 1)  # +1: overflow
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def add(self, value: Union[int, float]) -> None:
        value = float(value)
        idx = len(self.bounds)  # overflow unless a bound catches it
        for i, bound in enumerate(self.bounds):
            if value <= bound:
                idx = i
                break
        self.bucket_counts[idx] += 1
        self.count += 1
        self.sum += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)

    def percentile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0 < q < 1), linear inside the bucket."""
        if self.count == 0:
            return None
        target = q * self.count
        seen = 0.0
        for i, bucket_count in enumerate(self.bucket_counts):
            if bucket_count == 0:
                continue
            if seen + bucket_count >= target:
                lo = self.bounds[i - 1] if i > 0 else (
                    self.min if self.min is not None else 0.0
                )
                hi = self.bounds[i] if i < len(self.bounds) else (
                    self.max if self.max is not None else lo
                )
                lo = max(lo, self.min) if self.min is not None else lo
                hi = min(hi, self.max) if self.max is not None else hi
                if hi <= lo:
                    return lo
                frac = (target - seen) / bucket_count
                return lo + (hi - lo) * frac
            seen += bucket_count
        return self.max

    def snapshot(self) -> dict:
        cumulative = []
        running = 0
        for bound, bucket_count in zip(self.bounds, self.bucket_counts):
            running += bucket_count
            cumulative.append([bound, running])
        return {
            "kind": "histogram",
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "buckets": cumulative,  # [upper_bound, cumulative_count] pairs
        }


# ---------------------------------------------------------------------- #
# the registry
# ---------------------------------------------------------------------- #


class MeterRegistry:
    """Thread-safe name -> meter aggregation, kinds resolved against the
    ``METRICS`` declarations."""

    def __init__(self) -> None:
        self._lock = named_lock("meters.registry")
        self._meters: Dict[str, Any] = {}
        self._kinds: Dict[str, Tuple[str, Optional[Tuple[float, ...]]]] = {}
        self._dropped = 0  # observations refused by the cardinality guard
        self._dropped_names: set = set()  # distinct refused names (bounded)

    # -- kind resolution ------------------------------------------------ #

    def _resolve(self, name: str) -> Tuple[str, Optional[Tuple[float, ...]]]:
        cached = self._kinds.get(name)
        if cached is not None:
            return cached
        from modin_tpu.logging.metrics import METRICS

        kind = "counter"  # ad-hoc names (tests) default to the safest kind
        buckets: Optional[Tuple[float, ...]] = None
        for entry in METRICS:
            pattern = entry[0]
            if fnmatch.fnmatchcase(name, pattern):
                declared = entry[1] if len(entry) > 2 else "counter"
                if declared in VALID_KINDS:
                    kind = declared
                if kind == "histogram":
                    buckets = HISTOGRAM_BUCKETS.get(pattern)
                    if buckets is None:
                        kind = "counter"  # undeclared buckets: degrade
                break
        self._kinds[name] = (kind, buckets)
        return kind, buckets

    def _max_series(self) -> int:
        try:
            from modin_tpu.config import MetersMaxSeries

            return int(MetersMaxSeries.get())
        except ImportError:  # config unavailable during teardown
            return 2048

    # -- recording ------------------------------------------------------- #

    def record(self, name: str, value: Union[int, float]) -> None:
        with self._lock:
            meter = self._meters.get(name)
            if meter is None:
                max_series = self._max_series()
                if len(self._meters) >= max_series:
                    self._dropped += 1
                    # distinct-name accounting is itself bounded: a runaway
                    # of rotating names must not leak through the guard's
                    # own bookkeeping
                    if len(self._dropped_names) < 4 * max_series:
                        self._dropped_names.add(name)
                    return
                kind, buckets = self._resolve(name)
                if kind == "histogram":
                    meter = Histogram(buckets)
                elif kind == "gauge":
                    meter = Gauge()
                else:
                    meter = Counter()
                self._meters[name] = meter
            meter.add(value)

    # -- introspection --------------------------------------------------- #

    def snapshot(self) -> dict:
        """Deep-copied ``{"series": {name: meter-dict}, ...}`` snapshot."""
        with self._lock:
            return {
                "enabled": METERS_ON,
                "dropped_series": len(self._dropped_names),
                "dropped_observations": self._dropped,
                "series": {
                    name: meter.snapshot()
                    for name, meter in sorted(self._meters.items())
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._meters.clear()
            # the kind-resolution cache too: per-section reset cycles
            # (bench.py) with rotating interpolated names would otherwise
            # grow it without bound
            self._kinds.clear()
            self._dropped = 0
            self._dropped_names.clear()


_REGISTRY = MeterRegistry()


def get_registry() -> MeterRegistry:
    return _REGISTRY


def snapshot() -> dict:
    """Snapshot of the process-wide aggregation registry."""
    return _REGISTRY.snapshot()


def reset() -> None:
    """Clear the process-wide aggregation registry."""
    _REGISTRY.reset()


# ---------------------------------------------------------------------- #
# enable/disable plumbing
# ---------------------------------------------------------------------- #


def _refresh_enabled() -> None:
    """Recompute the fast-path flags and (un)install the emit hook."""
    global ACCOUNTING_ON, METERS_ON
    METERS_ON = _env_enabled or _registry_consumers > 0
    on = METERS_ON or _active_scopes > 0
    ACCOUNTING_ON = on
    metrics = sys.modules.get("modin_tpu.logging.metrics")
    if metrics is None and on:
        from modin_tpu.logging import metrics  # noqa: F811
    if metrics is not None:
        metrics._aggregate = _dispatch_metric if on else None
    # graftcost Auto mode piggybacks on ACCOUNTING_ON; only poke the module
    # if something already imported it (same no-import rule as the ledger
    # sampling seam) — costs recomputes on ITS import/config path otherwise
    costs = sys.modules.get("modin_tpu.observability.costs")
    if costs is not None:
        costs._refresh()


def _on_meters_param(param: Any) -> None:
    global _env_enabled
    # same lock as query_stats enter/exit: an unsynchronized refresh could
    # read a stale _active_scopes and strand ACCOUNTING_ON=False under an
    # open scope (or leave the emit hook uninstalled)
    with _scope_lock:
        _env_enabled = bool(param.get())
        _refresh_enabled()


def meters_enabled() -> bool:
    """Is registry aggregation active right now (the config switch, or a
    long-lived consumer such as the graftwatch service)?"""
    return METERS_ON


def _dispatch_metric(name: str, value: Union[int, float]) -> None:
    """The ``metrics._aggregate`` hook: registry + active QueryStats."""
    try:
        if METERS_ON:
            _REGISTRY.record(name, value)
        stack = getattr(_qs_tls, "stack", None)
        if stack:
            for qs in stack:
                qs._on_metric(name, value)
    except Exception:
        pass


# ---------------------------------------------------------------------- #
# seam hooks (callers check ACCOUNTING_ON first)
# ---------------------------------------------------------------------- #


def thread_dispatches() -> int:
    """Monotonic per-thread dispatch counter (EXPLAIN ANALYZE takes deltas)."""
    return getattr(_qs_tls, "dispatches", 0)


def note_dispatch() -> None:
    """One successful engine-seam deploy on this thread.

    Called by the resilience wrapper's success path while accounting is on;
    feeds the per-thread counter (plan-node attribution) and the metric
    stream (registry + QueryStats).  Compile attribution is separate: the
    jax.monitoring listener bills compiles via :func:`note_compile`.
    """
    _qs_tls.dispatches = getattr(_qs_tls, "dispatches", 0) + 1
    from modin_tpu.logging.metrics import emit_metric

    emit_metric("engine.dispatch", 1)


def note_compile(duration_s: float) -> None:
    """One XLA backend compile observed by the monitoring listener."""
    from modin_tpu.logging.metrics import emit_metric

    emit_metric("engine.compile", 1)
    emit_metric("engine.compile_s", duration_s)


def _device_resident_bytes() -> int:
    """Device-ledger resident bytes, via the one shared sampling seam
    (``spans._ledger_bytes``: never imports core.memory, swallows ledger
    errors) so the no-import-recursion rule lives in a single place."""
    from modin_tpu.observability import spans as _spans

    return _spans._ledger_bytes()[0]


# ---------------------------------------------------------------------- #
# per-query accounting
# ---------------------------------------------------------------------- #


class QueryStats:
    """Everything one query scope consumed, rolled up from the metric
    stream on the owning thread (plus HBM residency samples)."""

    __slots__ = (
        "label",
        "signature",
        "wall_s",
        "dispatches",
        "compiles",
        "compile_s",
        "bytes_parsed",
        "io_reads",
        "spills",
        "spill_bytes",
        "restores",
        "recoveries",
        "cache_hits",
        "hbm_high_water",
        "api_calls",
        "est_flops",
        "est_bytes",
        "padded_bytes",
        "padding_waste_bytes",
        "collective_bytes",
        "breaker_trips",
        "stream_windows",
        "stream_replays",
        "stream_overlap_s",
        "stream_wait_s",
        "fused_dispatches",
        "donated_bytes",
        "view_hits",
        "view_folds",
        "view_invalidations",
        "_t0",
        "_lock",
        "_closed",
    )

    def __init__(self, label: str = "query") -> None:
        global _alloc_count
        _alloc_count += 1
        self.label = label
        # routing can cross threads (the resilience watchdog seeds its
        # worker with the owner's scopes, and a timed-out worker is
        # abandoned mid-thunk): accumulation takes this lock, and a closed
        # scope stops accepting so late emissions from an abandoned worker
        # can never mutate a rollup the owner already read
        self._lock = named_lock("meters.query_stats")
        self._closed = False
        self.signature = None  # innermost QUERY-COMPILER span, if tracing
        self.wall_s = 0.0
        self.dispatches = 0
        self.compiles = 0
        self.compile_s = 0.0
        self.bytes_parsed = 0
        self.io_reads = 0
        self.spills = 0
        self.spill_bytes = 0
        self.restores = 0
        self.recoveries = 0
        self.cache_hits = {"fused": 0, "sorted_rep": 0, "plan_scan": 0}
        self.hbm_high_water = 0
        self.api_calls = 0
        # graftcost: estimated hardware cost + padding waste (0 until the
        # cost-capture seams observe work under this scope)
        self.est_flops = 0.0
        self.est_bytes = 0.0
        self.padded_bytes = 0
        self.padding_waste_bytes = 0
        # graftmesh: payload bytes this scope moved through collectives
        # (all_to_all/psum) — the cross-device traffic share of est_bytes
        self.collective_bytes = 0
        # graftgate tenant health: device-path breaker strikes observed
        # while this scope's query ran (its own fallbacks included — a
        # query can complete correct via fallback yet be striking paths)
        self.breaker_trips = 0
        # graftstream: resident windows this query streamed through, window
        # replays after mid-stream device failures, and the prefetch
        # overlap/wait split (overlap / (overlap + wait) is the pipeline's
        # overlap efficiency).  stream_windows > 0 also tells graftgate to
        # bill this query at its window footprint, not its dataset size.
        self.stream_windows = 0
        self.stream_replays = 0
        self.stream_overlap_s = 0.0
        self.stream_wait_s = 0.0
        # graftfuse: whole-plan dispatches (one program per query segment)
        # and the HBM released to XLA by buffer donation under this scope
        self.fused_dispatches = 0
        self.donated_bytes = 0
        # graftview: derived-artifact registry traffic under this scope —
        # whole results served from cache, appends absorbed by folds, and
        # artifacts honestly invalidated
        self.view_hits = 0
        self.view_folds = 0
        self.view_invalidations = 0
        self._t0 = time.perf_counter()

    # -- stream routing -------------------------------------------------- #

    def _on_metric(self, name: str, value: Union[int, float]) -> None:
        with self._lock:
            if self._closed:
                return
            self._route(name, value)

    def _route(self, name: str, value: Union[int, float]) -> None:
        if name == "engine.dispatch":
            self.dispatches += int(value)
            self._sample_hbm()
        elif name == "engine.compile":
            self.compiles += int(value)
        elif name == "engine.compile_s":
            self.compile_s += value
        elif name == "io.read.bytes":
            self.bytes_parsed += int(value)
            self.io_reads += 1
        elif name == "memory.device.spill":
            self.spills += int(value)
            self._sample_hbm()
        elif name == "memory.device.spill_bytes":
            self.spill_bytes += int(value)
        elif name == "memory.device.restore":
            self.restores += int(value)
            self._sample_hbm()
        elif name == "engine.cost.flops":
            self.est_flops += value
        elif name == "engine.cost.bytes":
            self.est_bytes += value
        elif name == "engine.cost.padded_bytes":
            self.padded_bytes += int(value)
        elif name == "engine.cost.padding_waste_bytes":
            self.padding_waste_bytes += int(value)
        elif name == "engine.cost.collective_bytes":
            self.collective_bytes += int(value)
        elif name == "sortcache.hit":
            self.cache_hits["sorted_rep"] += int(value)
        elif name == "fusion.cache.hit":
            self.cache_hits["fused"] += int(value)
        elif name == "plan.scan.cache_hit":
            self.cache_hits["plan_scan"] += int(value)
        elif name == "stream.window.count":
            self.stream_windows += int(value)
            self._sample_hbm()
        elif name == "fuse.dispatch":
            self.fused_dispatches += int(value)
            self._sample_hbm()
        elif name == "fuse.donated":
            # fired BEFORE the donated buffers leave the ledger: the last
            # honest pre-donation residency peak
            self._sample_hbm()
        elif name == "fuse.donated_bytes":
            self.donated_bytes += int(value)
            self._sample_hbm()
        elif name == "view.hit":
            self.view_hits += int(value)
        elif name == "view.fold":
            self.view_folds += int(value)
        elif name.startswith("view.invalidate."):
            self.view_invalidations += int(value)
        elif name == "stream.window.replay":
            self.stream_replays += int(value)
        elif name == "stream.prefetch.overlap_s":
            self.stream_overlap_s += value
        elif name == "stream.prefetch.wait_s":
            self.stream_wait_s += value
        elif name.startswith("recovery."):
            self.recoveries += int(value)
        elif (
            name.startswith("resilience.breaker.")
            and name.endswith(".strike")
            and not name.startswith("resilience.breaker.tenant_")
        ):
            # DEVICE-path strikes only: a nested submit's tenant-health
            # breaker (graftgate strikes it on the same thread while the
            # outer scope is still open) is a serving verdict, not device
            # sickness — counting it would cascade one tenant's failures
            # into the outer tenant's quarantine
            self.breaker_trips += int(value)
        elif name.startswith("pandas-api."):
            self.api_calls += 1

    def _sample_hbm(self) -> None:
        resident = _device_resident_bytes()
        if resident > self.hbm_high_water:
            self.hbm_high_water = resident

    def elapsed_s(self) -> float:
        """Wall seconds so far (final wall once the scope has closed)."""
        if self._closed:
            return self.wall_s
        return time.perf_counter() - self._t0

    # -- export ---------------------------------------------------------- #

    def as_dict(self) -> dict:
        return {
            "label": self.label,
            "signature": self.signature,
            "wall_s": self.wall_s,
            "dispatches": self.dispatches,
            "compiles": self.compiles,
            "compile_s": self.compile_s,
            "bytes_parsed": self.bytes_parsed,
            "io_reads": self.io_reads,
            "spills": self.spills,
            "spill_bytes": self.spill_bytes,
            "restores": self.restores,
            "recoveries": self.recoveries,
            "cache_hits": dict(self.cache_hits),
            "hbm_high_water": self.hbm_high_water,
            "api_calls": self.api_calls,
            "est_flops": self.est_flops,
            "est_bytes": self.est_bytes,
            "padded_bytes": self.padded_bytes,
            "padding_waste_bytes": self.padding_waste_bytes,
            "collective_bytes": self.collective_bytes,
            "breaker_trips": self.breaker_trips,
            "stream_windows": self.stream_windows,
            "stream_replays": self.stream_replays,
            "stream_overlap_s": self.stream_overlap_s,
            "stream_wait_s": self.stream_wait_s,
            "fused_dispatches": self.fused_dispatches,
            "donated_bytes": self.donated_bytes,
            "view_hits": self.view_hits,
            "view_folds": self.view_folds,
            "view_invalidations": self.view_invalidations,
        }

    def summary(self) -> str:
        """Human-readable rollup block for EXPLAIN ANALYZE output."""
        hits = ", ".join(f"{k}={v}" for k, v in sorted(self.cache_hits.items()))
        lines = [
            f"wall: {self.wall_s * 1e3:.3f} ms",
            f"device dispatches: {self.dispatches}, xla compiles: "
            f"{self.compiles} ({self.compile_s:.3f}s)",
            f"bytes parsed: {self.bytes_parsed} ({self.io_reads} read(s))",
            f"hbm high-water: {self.hbm_high_water} bytes, spills: "
            f"{self.spills} ({self.spill_bytes} bytes), restores: "
            f"{self.restores}, recoveries: {self.recoveries}",
            f"cache hits: {hits}",
            self._cost_line(),
        ]
        if self.fused_dispatches:
            lines.append(
                f"fuse: {self.fused_dispatches} whole-plan dispatch(es), "
                f"{self.donated_bytes} bytes donated"
            )
        if self.view_hits or self.view_folds or self.view_invalidations:
            lines.append(
                f"views: {self.view_hits} artifact hit(s), "
                f"{self.view_folds} incremental fold(s), "
                f"{self.view_invalidations} invalidation(s)"
            )
        if self.stream_windows:
            busy = self.stream_overlap_s + self.stream_wait_s
            eff = f"{self.stream_overlap_s / busy:.0%}" if busy > 0 else "?"
            lines.append(
                f"stream: {self.stream_windows} window(s), "
                f"{self.stream_replays} replay(s), overlap efficiency {eff} "
                f"({self.stream_overlap_s:.3f}s hidden, "
                f"{self.stream_wait_s:.3f}s waited)"
            )
        return "\n".join(lines)

    def _cost_line(self) -> str:
        """The graftcost rollup line: estimated work, padding share, and
        the achieved roofline fraction joined to this scope's wall."""
        from modin_tpu.observability import costs as _costs

        pad_pct = (
            f"{self.padding_waste_bytes / self.padded_bytes:.0%}"
            if self.padded_bytes > 0
            else "?"
        )
        roofline = "?"
        try:
            fraction = _costs.roofline_fraction(
                self.est_flops or None, self.est_bytes or None, self.wall_s
            )
            if fraction is not None:
                roofline = f"{fraction:.1%}"
        except Exception:
            pass
        return (
            f"est cost: {self.est_flops:.3g} flops, "
            f"{self.est_bytes:.3g} bytes moved; padding waste: "
            f"{self.padding_waste_bytes} of {self.padded_bytes} padded "
            f"bytes ({pad_pct}); roofline: {roofline}"
        )


def live_scopes() -> List["QueryStats"]:
    """Every QueryStats scope currently open on ANY thread (open order).

    The returned scopes are live objects owned by their opening threads —
    read them via :meth:`QueryStats.as_dict` (slot reads are atomic
    enough for telemetry); graftwatch's ``/debug/queries`` endpoint is
    the consumer.
    """
    with _scope_lock:
        return list(_live_scopes.values())


def snapshot_scopes() -> Optional[List["QueryStats"]]:
    """Copy of this thread's open QueryStats stack (outermost first), or None.

    Mirrors ``spans.snapshot_stack()``: worker threads that run a query's
    work on the caller's behalf (the resilience watchdog) seed themselves
    with this so metrics they emit still roll into the owning query's
    scopes.
    """
    stack = getattr(_qs_tls, "stack", None)
    return list(stack) if stack else None


def seed_thread_scopes(scopes: Optional[List["QueryStats"]]) -> None:
    """Adopt a snapshot of another thread's QueryStats stack.

    The seeded scopes are owned, entered, and exited by their original
    thread — this thread only routes its emissions into them.  Accumulation
    is lock-guarded and a closed scope stops accepting, so a worker the
    owner abandoned (watchdog timeout) can race the owner's retry or
    outlive the scope without corrupting its rollup.

    Always REPLACES the thread's stack — seeding with ``None``/empty
    clears it.  The previous keep-if-falsy behavior was a single-owner
    assumption: a pooled worker seeded for query A and later reused for
    unscoped work (or query B) kept routing emissions into A's closed
    scopes — closed-scope rejection hid the corruption, but a *still-open*
    outer scope on the original thread would have silently absorbed
    another query's metrics.
    """
    _qs_tls.stack = list(scopes) if scopes else []


@contextlib.contextmanager
def query_stats(label: str = "query") -> Iterator[QueryStats]:
    """Collect per-query resource accounting for the block on this thread.

    Activates accounting for its duration even when ``MODIN_TPU_METERS`` is
    off (that is the point: ad-hoc EXPLAIN ANALYZE without a process
    restart).  Scopes nest (inner metrics roll into every open scope on the
    stack) and are thread-isolated.  The scope is seeded from the innermost
    QUERY-COMPILER span open on this thread when tracing is active.
    """
    global _active_scopes
    qs = QueryStats(label)
    from modin_tpu.observability import spans as _spans

    if _spans.TRACE_ON:
        sig = _spans.attribution_signature()
        if sig != "<untraced>":
            qs.signature = sig
    with _scope_lock:
        _active_scopes += 1
        _live_scopes[id(qs)] = qs
        _refresh_enabled()
    stack = getattr(_qs_tls, "stack", None)
    if stack is None:
        stack = _qs_tls.stack = []
    stack.append(qs)
    try:
        yield qs
    finally:
        with qs._lock:
            qs.wall_s = time.perf_counter() - qs._t0
            qs._sample_hbm()
            qs._closed = True
        try:
            stack.remove(qs)
        except ValueError:
            pass
        with _scope_lock:
            _active_scopes -= 1
            _live_scopes.pop(id(qs), None)
            _refresh_enabled()


# wire the config switch (fires immediately with its current value)
from modin_tpu.config import MetersEnabled as _MetersEnabled  # noqa: E402

_MetersEnabled.subscribe(_on_meters_param)
