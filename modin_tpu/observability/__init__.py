"""graftscope — structured tracing & profiling for the query path.

Where a query spends its time, attributed across the four seams the
framework is built around:

1. **pandas API entry** — every ``enable_logging``-wrapped call emits a
   span tagged with its ``modin_layer`` (``PANDAS-API``, ...);
2. **TPU query compiler** — the same mechanism tags ``QUERY-COMPILER``
   spans, the granularity compile time is attributed to;
3. **the JaxWrapper engine seam** — the resilience wrapper emits one span
   per attempt (``engine.<op>.attempt``), so retries, watchdog kills, and
   classified failures appear as sibling spans with failure-kind
   attributes, and breaker fallbacks as ``fallback.<family>`` spans;
4. **shuffle / IO** — the range-partition shuffle and FileDispatcher reads.

Quick use::

    import modin_tpu.observability as gs

    with gs.profile() as prof:
        df.groupby("k").sum().to_pandas()
    print(prof.rollup())                       # host/device/compile split
    prof.export_chrome_trace("query.trace.json")   # load in chrome://tracing

    gs.get_compile_ledger().recompile_storms() # who keeps recompiling?

Always-on tracing: ``MODIN_TPU_TRACE=1`` (or
``modin_tpu.config.TraceEnabled.enable()``).  While on, finished spans also
feed a bounded flight-recorder ring that dumps automatically when a
resilience circuit breaker opens or a device failure is terminal — see
docs/observability.md.  Disabled (the default), the entire subsystem costs
one module-attribute check per instrumented call and allocates nothing.
"""

from modin_tpu.observability.chrome_trace import (  # noqa: F401
    export_chrome_trace,
    to_chrome_trace,
)
from modin_tpu.observability.compile_ledger import (  # noqa: F401
    CompileLedger,
    get_compile_ledger,
)
from modin_tpu.observability.flight_recorder import (  # noqa: F401
    dump_flight_record,
    flight_snapshot,
)
from modin_tpu.observability.spans import (  # noqa: F401
    SPANS,
    Profile,
    Span,
    counter_samples,
    current_span,
    layer_span,
    profile,
    span,
    span_alloc_count,
    start_span,
    finish_span,
    trace_enabled,
)
from modin_tpu.observability.meters import (  # noqa: F401
    HISTOGRAM_BUCKETS,
    QueryStats,
    meter_alloc_count,
    meters_enabled,
    query_stats,
)
from modin_tpu.observability.meters import (  # noqa: F401
    reset as meters_reset,
    snapshot as meters_snapshot,
)
from modin_tpu.observability.exposition import (  # noqa: F401
    meter_rollup,
    parse_prometheus,
    to_json,
    to_prometheus,
)
from modin_tpu.observability.costs import (  # noqa: F401
    CostLedger,
    get_cost_ledger,
    note_padding,
    roofline_fraction,
    substrate_peaks,
)
from modin_tpu.observability.watch import (  # noqa: F401
    WatchService,
    httpd_port,
    recent_trips,
    slo_health,
    watch_alloc_count,
    watch_snapshot,
)

# MODIN_TPU_TRACE=1 at import: the config subscription fired while
# compile_ledger was still initializing and deferred the listener install —
# complete it now that the package is whole
if trace_enabled():
    from modin_tpu.observability.compile_ledger import ensure_listener as _ensure

    _ensure()
    del _ensure
