"""graftscope span core: nestable spans with thread-local context propagation.

The query path crosses four seams — pandas API entry, the TPU query
compiler, the ``JaxWrapper`` engine seam, and shuffle/IO — and until now the
only record of a query's life was a flat START/STOP line log plus API timing
counters.  This module is the structured replacement: every instrumented
call becomes a **span** (name, layer tag, span id, parent id, wall-clock
interval, attributes), spans nest via a thread-local stack, and finished
spans are delivered to any active collectors (``profile()``) and to the
flight-recorder ring buffer (modin_tpu/observability/flight_recorder.py).

Layer tags reuse the ``modin_layer`` taxonomy the ``ClassLogger`` mixin
already stamps on every subsystem (``PANDAS-API``, ``QUERY-COMPILER``,
``JAX-ENGINE``, ``CORE-IO``, ...) plus ``SHUFFLE`` for the range-partition
shuffle, so a profile slices the same way the trace log always has.

Disabled-mode contract (the default, ``MODIN_TPU_TRACE=0``): the only cost
an instrumented call pays is one module-attribute check of ``TRACE_ON`` —
no span object is ever allocated (``span_alloc_count()`` lets tests assert
exactly that), and ``span()`` returns a shared no-op context manager
singleton.  ``TRACE_ON`` flips when the ``TraceEnabled`` config parameter
changes (pubsub subscription) or while any ``profile()`` is active.

Span names emitted with static (or f-string) names are declared in the
``SPANS`` registry below, cross-checked both ways by graftlint's
REGISTRY-DRIFT rule exactly like ``emit_metric`` names are against
``METRICS`` — an undeclared span name, a dead registry pattern, or an
undocumented family fails the lint.  The per-method spans emitted through
``layer_span`` by the logging decorator carry runtime-built names
(``<Class>.<method>``) and are documented as the layer taxonomy instead.
"""

from __future__ import annotations

import contextlib
import itertools
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from modin_tpu.concurrency import named_lock

#: Module-level fast path.  Instrumentation sites check this ONE attribute
#: before doing anything else; while it is False no span object is ever
#: allocated.  Flipped by the TraceEnabled config subscription and by
#: profile() activation — never written anywhere else.
TRACE_ON: bool = False

#: Registry of every span family emitted with a statically-known name
#: (``*`` stands for a runtime-interpolated segment, exactly like
#: logging/metrics.py:METRICS).  graftlint's REGISTRY-DRIFT rule
#: cross-checks this both ways — a ``span(...)``/``start_span(...)`` call
#: whose name matches no pattern, or a pattern with no live emit site,
#: fails the lint — and requires each family's stable prefix to appear in
#: docs/ (see docs/observability.md).  Per-method spans from the logging
#: decorator (``layer_span``) have runtime names and are covered by the
#: layer-tag taxonomy instead.
SPANS = (
    (
        "engine.*.attempt",
        "one engine-seam attempt (deploy/put/materialize/wait) under the "
        "resilience policy; retries appear as sibling attempt spans with "
        "attempt/failure_kind attributes, XLA compile time attributed via "
        "compile_s",
    ),
    (
        "fallback.*",
        "a device-path family declining to the pandas fallback: reason is "
        "the classified failure kind, or short_circuit when the family's "
        "breaker is open",
    ),
    (
        "shuffle.sample_pivots",
        "device key sample + host quantile pivot computation preceding a "
        "range shuffle",
    ),
    (
        "shuffle.range_shuffle",
        "the all_to_all range-partition shuffle: bucketize/pack, collective, "
        "compaction; slack retries recorded in attributes",
    ),
    (
        "io.read",
        "one FileDispatcher read (format dispatcher class in attributes)",
    ),
    (
        "recovery.reseat",
        "one graftguard lineage-recovery pass re-seating lost device "
        "columns after a DeviceLost (reason in attributes)",
    ),
    (
        "memory.device.spill",
        "one admission-control / evict-then-retry spill pass dropping "
        "cold device buffers to host (byte target in attributes)",
    ),
    (
        "router.decide",
        "one graftsort kernel-router decision: op family, rows, planned "
        "per-column strategies, predicted device/host costs and the "
        "chosen side in attributes",
    ),
    (
        "router.calibrate",
        "the one-shot kernel-router micro-benchmark pass seeding the "
        "cost model for this substrate (cached to CacheDir)",
    ),
    (
        "sortcache.build",
        "one batched sorted-representation build (the shared sort the "
        "rest of the sort-shaped family amortizes); column count in "
        "attributes",
    ),
    (
        "view.fold",
        "one graftview incremental-maintenance fold: the appended tail "
        "gathered and reduced (scalar combine) or grouped (partial-table "
        "combine) and merged into the cached artifact; op, column count, "
        "base and tail row counts in attributes",
    ),
    (
        "plan.optimize",
        "one graftplan rewrite pass to fixpoint over a pending logical "
        "plan (node count in attributes; applied rules become plan.rule.* "
        "metrics)",
    ),
    (
        "plan.lower",
        "one graftplan lowering pass: optimized plan nodes replayed "
        "through the eager dispatcher / query-compiler / engine seams "
        "(node count in attributes)",
    ),
    (
        "opt.choose",
        "one graftopt joint strategy pass over an optimized plan: every "
        "node annotated with estimated rows/bytes/seconds and its chosen "
        "kernel/layout/compile/residency legs (replanning flag and "
        "correction factor in attributes)",
    ),
    (
        "opt.replan",
        "one graftopt mid-query re-plan: the not-yet-lowered segment "
        "re-chosen against live evidence (trigger, remaining node count, "
        "divergence evidence, re-plan wall in attributes)",
    ),
    (
        "fuse.lower",
        "one graftfuse whole-plan fused lowering: the post-scan segment "
        "(filter/map/project chain plus its reduce or groupby tail) "
        "compiled and dispatched as a single donated program (segment "
        "signature, rows, donated column count in attributes)",
    ),
    (
        "stream.window",
        "one graftstream resident window: parse/deploy/consume/drop of a "
        "record-aligned byte range (scan loop) or one external-sort window "
        "slice (window index in attributes)",
    ),
    (
        "stream.merge",
        "one graftstream k-way fold of spilled sorted runs into the final "
        "permutation (run count in attributes)",
    ),
    (
        "serving.admit",
        "one graftgate admission decision: tenant, queue wait, and the "
        "degraded-route flag in attributes; error status means the query "
        "was shed or its deadline expired while queued",
    ),
    (
        "serving.query",
        "one admitted query's execution envelope under the serving "
        "context (tenant / label / degraded in attributes); everything "
        "the query dispatched nests under it",
    ),
    (
        "ingest.append",
        "one graftfeed micro-batch admitted into a feed: schema-validated "
        "rows concatenated onto the frame, views folded per policy, "
        "retention applied (feed / row count in attributes)",
    ),
    (
        "ingest.fold",
        "one pending micro-batch folded into every registered live view's "
        "running state (feed / batch seq in attributes)",
    ),
    (
        "ingest.read",
        "one staleness-bounded live-view read: fold-lag check, optional "
        "forced synchronous fold, state snapshot (feed / view in "
        "attributes)",
    ),
    (
        "checkpoint.write",
        "one graftwal checkpoint: pending folds drained, feed frame + "
        "every view's fold state snapshotted under the feed lock, "
        "serialized and atomically written outside it, covered WAL "
        "segments truncated (feed in attributes)",
    ),
    (
        "recovery.replay",
        "one graftwal crash recovery: newest valid checkpoint restored, "
        "WAL tail replayed through the ordinary ingest path, torn tail "
        "truncated with accounting (feed in attributes)",
    ),
)

_EPOCH_PERF = time.perf_counter()
_EPOCH_WALL = time.time()

_span_ids = itertools.count(1)
_alloc_count = 0  # Span objects ever constructed (the zero-alloc assertion)

_tls = threading.local()

_collectors: List[list] = []  # active profile() collectors
_state_lock = named_lock("spans.state")

#: bounded ring of recently finished spans (the flight recorder's memory);
#: created/resized by _refresh_enabled from TraceFlightRecorderSize
_RING: Optional[deque] = None

#: bounded ring of counter samples ``(ts_us, (device_bytes, host_bytes,
#: live_spans))`` taken at each span finish while tracing is on; rendered
#: as Chrome-trace counter tracks ("C" events) so HBM pressure is visible
#: on the Perfetto timeline alongside the spans that caused it
_COUNTERS: Optional[deque] = None

#: open spans across all threads right now (the third counter track);
#: maintained only while the counter ring exists (zeroed on ring
#: reconfiguration), read-modify-write only under _live_lock — threads
#: finish spans concurrently and a lost update would drift the counter
_live_spans = 0
_live_lock = named_lock("spans.live")

_env_enabled = False


class Span:
    """One timed, attributed interval on the query path."""

    __slots__ = (
        "name",
        "layer",
        "span_id",
        "parent_id",
        "start_us",
        "dur_us",
        "wall_start_s",
        "attrs",
        "thread_id",
        "thread_name",
        "status",
        "_counted",
    )

    def __init__(self, name: str, layer: str, attrs: Optional[dict], parent_id: Optional[int]):
        t = threading.current_thread()
        self.name = name
        self.layer = layer
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.start_us = (time.perf_counter() - _EPOCH_PERF) * 1e6
        self.wall_start_s = _EPOCH_WALL + self.start_us / 1e6
        self.dur_us = 0.0
        self.attrs = attrs if attrs is not None else {}
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self.status = "open"
        self._counted = False  # did this span increment _live_spans?

    def __repr__(self) -> str:  # debugging aid, not part of the export
        return (
            f"<Span {self.name} [{self.layer}] id={self.span_id} "
            f"parent={self.parent_id} dur={self.dur_us / 1e3:.3f}ms "
            f"{self.status}>"
        )


# ---------------------------------------------------------------------- #
# enable/disable plumbing
# ---------------------------------------------------------------------- #


def _refresh_enabled() -> None:
    """Recompute TRACE_ON (and size the ring) from config + collectors."""
    global TRACE_ON, _RING, _COUNTERS, _live_spans
    on = _env_enabled or bool(_collectors)
    if on:
        from modin_tpu.config import TraceFlightRecorderSize

        size = int(TraceFlightRecorderSize.get())
        if size <= 0:
            _RING = None
            _COUNTERS = None
            with _live_lock:
                _live_spans = 0
        elif _RING is None or _RING.maxlen != size:
            if _RING is None:
                # live-span bookkeeping only runs while the ring exists:
                # restart the counter from zero on (re)enable rather than
                # trust a value that missed the opens in between
                with _live_lock:
                    _live_spans = 0
            # retune a live process: keep the newest spans that still fit
            _RING = deque(_RING or (), maxlen=size)
            _COUNTERS = deque(_COUNTERS or (), maxlen=size)
    TRACE_ON = on


def _on_trace_param(param: Any) -> None:
    global _env_enabled
    _env_enabled = bool(param.get())
    _refresh_enabled()
    if _env_enabled:
        try:
            from modin_tpu.observability.compile_ledger import ensure_listener
        except ImportError:
            # subscription fired during the package's own import (env sets
            # MODIN_TPU_TRACE=1) while compile_ledger is mid-initialization;
            # observability/__init__ installs the listener right after
            return
        ensure_listener()


def trace_enabled() -> bool:
    """Is span collection active right now (config switch or a profile)?"""
    return TRACE_ON


def span_alloc_count() -> int:
    """How many Span objects this process has ever constructed.

    The disabled-mode contract is *zero new allocations*; tests snapshot
    this counter around a workload run with tracing off.
    """
    return _alloc_count


# ---------------------------------------------------------------------- #
# the span stack
# ---------------------------------------------------------------------- #


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def current_span() -> Optional[Span]:
    """Innermost open span on this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def snapshot_stack() -> Optional[list]:
    """Copy of this thread's open-span stack (outermost first), or None."""
    stack = getattr(_tls, "stack", None)
    return list(stack) if stack else None


def seed_thread(stack: Optional[list]) -> None:
    """Adopt a snapshot of another thread's span stack as ambient context.

    Worker threads (the resilience watchdog) call this so spans they start
    — and compile-time attribution — nest under the call chain that spawned
    the work instead of floating parentless.  The seeded spans are owned
    and finished by their original thread; this thread only reads them.
    """
    if stack:
        _tls.stack = list(stack)


def attribution_signature() -> str:
    """The op signature compile time should be billed to.

    Innermost QUERY-COMPILER span if one is open on this thread (the
    per-operator granularity the compile ledger wants), else the innermost
    span of any layer, else ``<untraced>``.
    """
    stack = getattr(_tls, "stack", None)
    if not stack:
        return "<untraced>"
    for sp in reversed(stack):
        if sp.layer == "QUERY-COMPILER":
            return sp.name
    return stack[-1].name


# ---------------------------------------------------------------------- #
# span lifecycle
# ---------------------------------------------------------------------- #


def start_span(
    name: str,
    layer: str = "APP",
    attrs: Optional[dict] = None,
    parent_id: Optional[int] = None,
) -> Span:
    """Open a span and push it on this thread's stack.

    Callers on hot paths must check ``TRACE_ON`` first; this function
    allocates unconditionally (that is its job).
    """
    global _alloc_count, _live_spans
    stack = _stack()
    if parent_id is None and stack:
        parent_id = stack[-1].span_id
    sp = Span(name, layer, attrs, parent_id)
    _alloc_count += 1  # single-threaded assertion counter: no lock needed
    if _COUNTERS is not None:
        # the live-span counter track exists only while the ring does;
        # don't serialize every traced thread on the lock otherwise
        sp._counted = True
        with _live_lock:
            _live_spans += 1
    stack.append(sp)
    return sp


def finish_span(sp: Span, status: str = "ok") -> None:
    """Close a span, pop it, and deliver it to collectors + the ring."""
    global _live_spans
    sp.dur_us = (time.perf_counter() - _EPOCH_PERF) * 1e6 - sp.start_us
    sp.status = status
    # only spans that incremented may decrement: a span opened before the
    # counter ring existed must not consume the count of one opened after
    if sp._counted and _COUNTERS is not None:
        with _live_lock:
            _live_spans = max(_live_spans - 1, 0)
    stack = getattr(_tls, "stack", None)
    if stack:
        if stack[-1] is sp:
            stack.pop()
        else:  # out-of-order finish (escaped generator etc.): best effort
            try:
                stack.remove(sp)
            except ValueError:
                pass
    _deliver(sp)


def _deliver(sp: Span) -> None:
    ring = _RING
    if ring is not None:
        ring.append(sp)
    counters = _COUNTERS
    if counters is not None:
        counters.append(
            (
                sp.start_us + sp.dur_us,
                _ledger_bytes()
                + (_live_spans,)
                + _cost_samples()
                + _gate_samples(),
            )
        )
    if _collectors:
        with _state_lock:
            for collector in _collectors:
                collector.append(sp)


def _ledger_bytes() -> tuple:
    """(device-resident bytes, host-cache bytes) — 0s until core.memory is
    imported (never imported from here: the ledgers import the metric
    stream, and a sampling-time import could recurse through it)."""
    memory = sys.modules.get("modin_tpu.core.memory")
    if memory is None:
        return (0, 0)
    try:
        return (memory.device_ledger.total_bytes(), memory.host_cache_bytes())
    except Exception:
        return (0, 0)


def _cost_samples() -> tuple:
    """(total padding-waste bytes, last achieved bandwidth) from graftcost —
    0s until observability.costs is imported (same no-import rule as
    :func:`_ledger_bytes`: sampling must never trigger an import chain)."""
    costs = sys.modules.get("modin_tpu.observability.costs")
    if costs is None:
        return (0, 0)
    try:
        return costs.counter_sample()
    except Exception:
        return (0, 0)


def _gate_samples() -> tuple:
    """(admission-queue depth, in-flight queries) from graftgate — 0s
    until serving.gate is imported (same no-import rule as
    :func:`_ledger_bytes`), read lock-free by design."""
    gate_mod = sys.modules.get("modin_tpu.serving.gate")
    if gate_mod is None:
        return (0, 0)
    try:
        return gate_mod.counter_sample()
    except Exception:
        return (0, 0)


def counter_samples(
    start_us: Optional[float] = None, end_us: Optional[float] = None
) -> List[tuple]:
    """Counter samples ``(ts_us, (device_bytes, host_bytes, live_spans,
    padding_waste_bytes, achieved_bw, gate_queued, gate_running))``
    currently in the ring, optionally clipped to a time window (a profile
    exports only the samples its own spans cover)."""
    counters = _COUNTERS
    if counters is None:
        return []
    out = list(counters)
    if start_us is not None:
        out = [s for s in out if s[0] >= start_us]
    if end_us is not None:
        out = [s for s in out if s[0] <= end_us]
    return out


class _SpanHandle:
    """Context manager over one open span; yields the Span for attributes."""

    __slots__ = ("_span",)

    def __init__(self, sp: Span):
        self._span = sp

    def __enter__(self) -> Span:
        return self._span

    def __exit__(self, exc_type: Any, exc: Any, tb: Any) -> bool:
        if exc_type is not None:
            self._span.attrs.setdefault("exc", exc_type.__name__)
            finish_span(self._span, status="error")
        else:
            finish_span(self._span)
        return False


class _NullHandle:
    """Shared no-op context manager returned while tracing is disabled."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc_info: Any) -> bool:
        return False


_NULL_HANDLE = _NullHandle()


def span(name: str, layer: str = "APP", **attrs: Any) -> Any:
    """Open a named span as a context manager (no-op when tracing is off).

    Statically-named call sites are cross-checked against the ``SPANS``
    registry by graftlint's REGISTRY-DRIFT rule; use ``layer_span`` for
    runtime-built names (the logging decorator's per-method spans).
    """
    if not TRACE_ON:
        return _NULL_HANDLE
    return _SpanHandle(start_span(name, layer, attrs or None))


def layer_span(name: str, layer: str) -> Any:
    """``span`` variant for runtime-built names (exempt from the registry)."""
    if not TRACE_ON:
        return _NULL_HANDLE
    return _SpanHandle(start_span(name, layer, None))


# ---------------------------------------------------------------------- #
# profiles
# ---------------------------------------------------------------------- #

#: the user-facing entry layers; shared with the logging decorator's
#: is_api_layer check so the list cannot drift between the two subsystems
API_LAYERS = frozenset({"PANDAS-API", "NUMPY-API", "POLARS-API"})


class Profile:
    """The spans collected by one ``profile()`` block, plus rollups."""

    def __init__(self) -> None:
        self.spans: List[Span] = []

    # -- structure ----------------------------------------------------- #

    def tree(self) -> List[dict]:
        """Nested {span, children} dicts rooted at spans with no collected
        parent, in start order."""
        by_id: Dict[int, dict] = {
            sp.span_id: {"span": sp, "children": []} for sp in self.spans
        }
        roots: List[dict] = []
        for sp in sorted(self.spans, key=lambda s: s.start_us):
            node = by_id[sp.span_id]
            parent = by_id.get(sp.parent_id) if sp.parent_id else None
            if parent is not None:
                parent["children"].append(node)
            else:
                roots.append(node)
        return roots

    def find(self, prefix: str) -> List[Span]:
        """Collected spans whose name starts with ``prefix``."""
        return [sp for sp in self.spans if sp.name.startswith(prefix)]

    def ancestors(self, sp: Span) -> List[Span]:
        """Chain of collected ancestors of ``sp``, innermost first."""
        by_id = {s.span_id: s for s in self.spans}
        out: List[Span] = []
        cur = by_id.get(sp.parent_id) if sp.parent_id else None
        while cur is not None:
            out.append(cur)
            cur = by_id.get(cur.parent_id) if cur.parent_id else None
        return out

    # -- rollups -------------------------------------------------------- #

    def rollup(self) -> dict:
        """Host / device / compile wall-clock attribution.

        - ``wall_s``: summed duration of root spans (no collected parent);
        - ``engine_s``: time inside engine-seam attempts (device dispatch,
          transfers, blocking fetches — includes any XLA compiles that
          happened there);
        - ``compile_s``: XLA compile wall time attributed to collected spans
          by the compile ledger's monitoring listener;
        - ``device_s``: ``engine_s`` minus the compile time spent inside the
          engine attempts (pure device/runtime time);
        - ``host_s``: everything else (``wall_s - engine_s``), the
          framework + pandas-fallback share;
        - ``by_layer_self_s``: per-layer *self* time (each span's duration
          minus its collected children's) — where the time actually went.
        """
        spans = self.spans
        by_id = {sp.span_id: sp for sp in spans}
        child_us: Dict[int, float] = {}
        for sp in spans:
            if sp.parent_id in by_id:
                child_us[sp.parent_id] = child_us.get(sp.parent_id, 0.0) + sp.dur_us
        wall_us = sum(sp.dur_us for sp in spans if sp.parent_id not in by_id)
        engine_attempts = [
            sp
            for sp in spans
            if sp.name.startswith("engine.") and sp.name.endswith(".attempt")
        ]
        engine_us = sum(sp.dur_us for sp in engine_attempts)
        compile_s = sum(sp.attrs.get("compile_s", 0.0) for sp in spans)
        engine_compile_s = sum(
            sp.attrs.get("compile_s", 0.0) for sp in engine_attempts
        )
        by_layer: Dict[str, float] = {}
        for sp in spans:
            self_us = max(sp.dur_us - child_us.get(sp.span_id, 0.0), 0.0)
            by_layer[sp.layer] = by_layer.get(sp.layer, 0.0) + self_us
        return {
            "wall_s": wall_us / 1e6,
            "engine_s": engine_us / 1e6,
            "device_s": max(engine_us / 1e6 - engine_compile_s, 0.0),
            "compile_s": compile_s,
            "host_s": max((wall_us - engine_us) / 1e6, 0.0),
            "spans": len(spans),
            "by_layer_self_s": {
                layer: round(us / 1e6, 6) for layer, us in sorted(by_layer.items())
            },
        }

    # -- export --------------------------------------------------------- #

    def _counter_window(self) -> List[tuple]:
        """Counter samples covered by this profile's spans."""
        if not self.spans:
            return []
        return counter_samples(
            start_us=min(sp.start_us for sp in self.spans),
            end_us=max(sp.start_us + sp.dur_us for sp in self.spans),
        )

    def to_chrome_trace(self) -> dict:
        from modin_tpu.observability.chrome_trace import to_chrome_trace

        return to_chrome_trace(
            self.spans,
            other_data={"rollup": self.rollup()},
            counters=self._counter_window(),
        )

    def export_chrome_trace(self, path: Any) -> str:
        from modin_tpu.observability.chrome_trace import export_chrome_trace

        return export_chrome_trace(
            self.spans,
            path,
            other_data={"rollup": self.rollup()},
            counters=self._counter_window(),
        )


@contextlib.contextmanager
def profile() -> Iterator[Profile]:
    """Collect every span finished while the block runs.

    Activates tracing for the duration even when ``MODIN_TPU_TRACE`` is off
    (that is the point: an ad-hoc profile without a process restart), and
    installs the XLA compile listener so compile time is attributed.
    """
    from modin_tpu.observability.compile_ledger import ensure_listener

    ensure_listener()
    prof = Profile()
    with _state_lock:
        _collectors.append(prof.spans)
    _refresh_enabled()
    try:
        yield prof
    finally:
        with _state_lock:
            try:
                _collectors.remove(prof.spans)
            except ValueError:
                pass
        _refresh_enabled()


# wire the config switches (each fires immediately with its current value)
from modin_tpu.config import (  # noqa: E402
    TraceEnabled as _TraceEnabled,
    TraceFlightRecorderSize as _TraceFlightRecorderSize,
)

_TraceEnabled.subscribe(_on_trace_param)
_TraceFlightRecorderSize.subscribe(lambda _param: _refresh_enabled())
