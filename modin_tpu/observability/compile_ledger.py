"""XLA compile observability: the compile-cache ledger.

Every fresh XLA compile is expensive (20-40s over a tunneled TPU), and
today they are *invisible*: a shape or dtype drifting per call recompiles
the same logical op forever and nothing reports it.  jax publishes a
monitoring event (``/jax/core/compile/backend_compile_duration``) on every
backend compile and stays silent on executable-cache hits; this module
turns that into a per-op-signature ledger:

- **compiles / compile_s** — counted by a ``jax.monitoring`` duration
  listener, attributed to the innermost QUERY-COMPILER span open on the
  compiling thread (``spans.attribution_signature()``), so a compile is
  billed to ``TpuQueryCompiler.sum`` rather than to the generic engine
  ``deploy``.  The same listener adds ``compile_s`` to the innermost open
  span, which is how profiles separate compile from device time.
- **dispatches / cache_hits** — while tracing is on, the resilience
  engine-seam wrapper reports every ``deploy`` with whether any compile
  fired during the attempt; a dispatch with zero compiles is a cache hit
  for its signature.
- **recompile storms** — ``recompile_storms(min_compiles)`` names the
  signatures compiled suspiciously often; ``snapshot()`` feeds dashboards.

The listener is process-global and effectively free when idle (it runs only
when XLA actually compiles); it is installed at engine startup
(``initialize_jax``), when ``MODIN_TPU_TRACE`` turns on, and by
``profile()``.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.observability import spans as _spans

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_tls = threading.local()


@contextlib.contextmanager
def suppress_listener() -> Iterator[None]:
    """Hide compile events fired on this thread from the ledger.

    graftcost's ``Full`` capture mode AOT-compiles a program the engine
    already compiled (``memory_analysis()`` needs the executable); without
    suppression that duplicate backend compile would be billed as workload
    — doubling ``engine.compile`` counts and poisoning the cache-hit
    accounting the metrics gate checks.
    """
    _tls.suppress = getattr(_tls, "suppress", 0) + 1
    try:
        yield
    finally:
        _tls.suppress -= 1


class CompileLedger:
    """Thread-safe per-signature compile/dispatch accounting."""

    def __init__(self) -> None:
        self._lock = named_lock("compile_ledger.entries")
        self._entries: Dict[str, dict] = {}
        self.total_compiles = 0
        self.total_compile_s = 0.0

    def _entry(self, signature: str) -> dict:
        entry = self._entries.get(signature)
        if entry is None:
            entry = self._entries[signature] = {
                "compiles": 0,
                "compile_s": 0.0,
                "dispatches": 0,
                "cache_hits": 0,
            }
        return entry

    def record_cost(self, signature: str, cost: dict) -> None:
        """Attach graftcost static-cost fields (flops / bytes accessed /
        transcendentals, memory sizes under Full capture) to a signature's
        entry.  Unknown fields stay ``"unknown"`` — never absent-by-crash."""
        from modin_tpu.observability.costs import _merge_known

        with self._lock:
            entry = self._entry(signature)
            _merge_known(entry.setdefault("cost", {}), cost)

    def record_compile(self, signature: str, duration_s: float) -> None:
        with self._lock:
            entry = self._entry(signature)
            entry["compiles"] += 1
            entry["compile_s"] += duration_s
            self.total_compiles += 1
            self.total_compile_s += duration_s

    def record_dispatch(self, signature: str, compiled: bool) -> None:
        with self._lock:
            entry = self._entry(signature)
            entry["dispatches"] += 1
            if not compiled:
                entry["cache_hits"] += 1

    def totals(self) -> tuple:
        """``(total_compiles, total_compile_s)`` without building the full
        per-signature snapshot — the graftwatch sampler reads this every
        tick, so it must stay O(1) under the lock."""
        with self._lock:
            return (self.total_compiles, self.total_compile_s)

    def snapshot(self) -> dict:
        """Deep copy: {signature: {compiles, compile_s, dispatches,
        cache_hits}} plus process totals."""
        with self._lock:
            return {
                "total_compiles": self.total_compiles,
                "total_compile_s": self.total_compile_s,
                "signatures": {sig: dict(e) for sig, e in self._entries.items()},
            }

    def recompile_storms(self, min_compiles: int = 3) -> Dict[str, int]:
        """Signatures backend-compiled at least ``min_compiles`` times —
        shape/dtype churn defeating the executable cache."""
        with self._lock:
            return {
                sig: e["compiles"]
                for sig, e in self._entries.items()
                if e["compiles"] >= min_compiles
            }

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self.total_compiles = 0
            self.total_compile_s = 0.0


_LEDGER = CompileLedger()


def get_compile_ledger() -> CompileLedger:
    return _LEDGER


def compiles_on_this_thread() -> int:
    """Monotonic per-thread compile counter (hit detection takes deltas)."""
    return getattr(_tls, "compiles", 0)


def _on_event_duration(event: str, duration: float, **kwargs: object) -> None:
    if event != COMPILE_EVENT:
        return
    if getattr(_tls, "suppress", 0):
        return  # graftcost's own AOT capture compile: not workload
    try:
        _tls.compiles = getattr(_tls, "compiles", 0) + 1
        _LEDGER.record_compile(_spans.attribution_signature(), duration)
        if _spans.TRACE_ON:
            sp = _spans.current_span()
            if sp is not None:
                sp.attrs["compile_s"] = sp.attrs.get("compile_s", 0.0) + duration
        from modin_tpu.observability import meters as _meters

        if _meters.ACCOUNTING_ON:
            _meters.note_compile(duration)
    except Exception:
        # a broken listener must never break the compile it observes
        pass


_installed = False
_install_lock = named_lock("compile_ledger.install")


def ensure_listener() -> bool:
    """Idempotently register the jax.monitoring compile listener.

    Returns True when the listener is (now) installed; False when jax is
    unavailable (the ledger then simply stays empty).
    """
    global _installed
    if _installed:
        return True
    with _install_lock:
        if _installed:
            return True
        try:
            from jax._src import monitoring
        except Exception:
            return False
        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _installed = True
        return True
