"""Chrome Trace Event Format export for graftscope spans.

Produces the JSON Object Format of the Trace Event spec (the format
``chrome://tracing`` and Perfetto's legacy importer load directly):
``{"traceEvents": [...], "displayTimeUnit": "ms"}`` where each finished
span becomes one complete event (``"ph": "X"``) with microsecond ``ts`` /
``dur``, the span's layer as the category, and span/parent ids plus
attributes under ``args``.  Thread-name metadata events (``"ph": "M"``)
label each thread lane.
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any, Iterable, List, Optional


def _json_safe(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


def to_chrome_trace(spans: Iterable[Any], other_data: Optional[dict] = None) -> dict:
    """Render finished spans as a chrome://tracing-loadable trace object."""
    pid = os.getpid()
    events: List[dict] = []
    thread_names = {}
    for sp in spans:
        thread_names.setdefault(sp.thread_id, sp.thread_name)
        # dict() is a C-level copy (safe against a watchdog-abandoned worker
        # still appending compile_s to a finished span's attrs mid-iteration)
        args = {str(k): _json_safe(v) for k, v in dict(sp.attrs).items()}
        args["span_id"] = sp.span_id
        if sp.parent_id:
            args["parent_id"] = sp.parent_id
        if sp.status != "ok":
            args["status"] = sp.status
        events.append(
            {
                "name": sp.name,
                "cat": sp.layer,
                "ph": "X",
                "ts": round(sp.start_us, 3),
                "dur": round(sp.dur_us, 3),
                "pid": pid,
                "tid": sp.thread_id,
                "args": args,
            }
        )
    for tid, tname in sorted(thread_names.items()):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
        )
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    if other_data:
        trace["otherData"] = {str(k): _json_safe_tree(v) for k, v in other_data.items()}
    return trace


def _json_safe_tree(value: Any) -> Any:
    if isinstance(value, dict):
        return {str(k): _json_safe_tree(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe_tree(v) for v in value]
    return _json_safe(value)


def export_chrome_trace(
    spans: Iterable[Any], path: Any, other_data: Optional[dict] = None
) -> str:
    """Write the trace JSON to ``path`` (parent dirs created); returns path."""
    p = pathlib.Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(to_chrome_trace(spans, other_data=other_data)))
    return str(p)
