"""Chrome Trace Event Format export for graftscope spans.

Produces the JSON Object Format of the Trace Event spec (the format
``chrome://tracing`` and Perfetto's legacy importer load directly):
``{"traceEvents": [...], "displayTimeUnit": "ms"}`` where each finished
span becomes one complete event (``"ph": "X"``) with microsecond ``ts`` /
``dur``, the span's layer as the category, and span/parent ids plus
attributes under ``args``.  Thread-name metadata events (``"ph": "M"``)
label each thread lane.

Counter samples (``spans.counter_samples()``, one per span finish) become
counter-track events (``"ph": "C"``): device-ledger resident bytes,
host-cache bytes, and the live span count render as value tracks above the
span lanes, so HBM pressure is visible on the Perfetto timeline alongside
the spans that caused it.
"""

from __future__ import annotations

import json
import os
import pathlib
from typing import Any, Iterable, List, Optional


def _json_safe(value: Any) -> Any:
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return str(value)


#: counter-track names, in sample-tuple order (see spans.counter_samples).
#: zip() pairs tracks with sample values and stops at the shorter side, so
#: samples recorded before a track existed simply omit it.
COUNTER_TRACKS = (
    "memory.device.resident_bytes",
    "memory.host.cache_bytes",
    "spans.live",
    # graftcost: cumulative padding-waste bytes and the most recent
    # achieved-bandwidth sample (bytes/s) — roofline pressure next to the
    # HBM tracks on the same Perfetto timeline
    "engine.cost.padding_waste_bytes",
    "engine.cost.achieved_bw_bytes_s",
    # graftgate: admission-queue depth and in-flight query count sampled
    # at each span finish — profile exports show admission pressure over
    # time next to the spans it delayed
    "serving.gate.queued",
    "serving.gate.running",
)


def to_chrome_trace(
    spans: Iterable[Any],
    other_data: Optional[dict] = None,
    counters: Optional[Iterable[tuple]] = None,
) -> dict:
    """Render finished spans as a chrome://tracing-loadable trace object.

    ``counters`` is an iterable of ``(ts_us, (device_bytes, host_bytes,
    live_spans, padding_waste_bytes, achieved_bw, gate_queued,
    gate_running))`` samples; each becomes one "C" event per
    :data:`COUNTER_TRACKS` track.
    """
    pid = os.getpid()
    events: List[dict] = []
    thread_names = {}
    for sp in spans:
        thread_names.setdefault(sp.thread_id, sp.thread_name)
        # dict() is a C-level copy (safe against a watchdog-abandoned worker
        # still appending compile_s to a finished span's attrs mid-iteration)
        args = {str(k): _json_safe(v) for k, v in dict(sp.attrs).items()}
        args["span_id"] = sp.span_id
        if sp.parent_id:
            args["parent_id"] = sp.parent_id
        if sp.status != "ok":
            args["status"] = sp.status
        events.append(
            {
                "name": sp.name,
                "cat": sp.layer,
                "ph": "X",
                "ts": round(sp.start_us, 3),
                "dur": round(sp.dur_us, 3),
                "pid": pid,
                "tid": sp.thread_id,
                "args": args,
            }
        )
    for ts, values in counters or ():
        for track, value in zip(COUNTER_TRACKS, values):
            events.append(
                {
                    "name": track,
                    "ph": "C",
                    "ts": round(ts, 3),
                    "pid": pid,
                    "args": {"value": value},
                }
            )
    for tid, tname in sorted(thread_names.items()):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": tname},
            }
        )
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    if other_data:
        trace["otherData"] = {str(k): _json_safe_tree(v) for k, v in other_data.items()}
    return trace


def _json_safe_tree(value: Any) -> Any:
    if isinstance(value, dict):
        return {str(k): _json_safe_tree(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe_tree(v) for v in value]
    return _json_safe(value)


def export_chrome_trace(
    spans: Iterable[Any],
    path: Any,
    other_data: Optional[dict] = None,
    counters: Optional[Iterable[tuple]] = None,
) -> str:
    """Write the trace JSON to ``path`` (parent dirs created); returns path."""
    p = pathlib.Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(
        json.dumps(to_chrome_trace(spans, other_data=other_data, counters=counters))
    )
    return str(p)
