"""graftcost: XLA cost-model observability — roofline efficiency & padding waste.

graftscope answers *where the time went* and graftmeter *what the query
consumed*; this module answers **how well the hardware was used**.  Three
legs, all riding the seams the earlier layers already cut:

1. **Static cost capture.**  When the engine seam bills an XLA compile to a
   signature (``compile_ledger``), the deploy path also asks jax what the
   compiled program *costs*: ``Lowered.cost_analysis()`` (flops, bytes
   accessed, transcendentals — available WITHOUT a backend compile, so the
   default capture adds only a re-trace/lower, never a second 20-40s tunnel
   compile) and, under ``MODIN_TPU_COST_CAPTURE=Full``,
   ``compiled.memory_analysis()`` (peak/temp/argument bytes — this one
   needs a real AOT compile, so it is opt-in and the compile-ledger
   listener is suppressed while it runs to keep the billing honest).
   Anything missing — None analysis, absent keys, a backend that cannot
   answer — degrades to ``"unknown"``; capture NEVER raises into the
   dispatch it observes.

2. **Achieved efficiency.**  Captured flops/bytes join the engine-seam
   dispatch wall into achieved FLOP/s, achieved bandwidth, and a roofline
   fraction (vs :func:`substrate_peaks`: a built-in table for known TPU
   generations, a cached one-shot micro-benchmark on CPU).  On an async
   substrate the attempt wall is enqueue time, so per-signature fractions
   are flagged ``async_caveat``; the EXPLAIN ANALYZE per-node join uses the
   node's measured wall instead, which includes the materialization sync.

3. **Padding-waste accounting.**  The pow2/bucket/shard-multiple padding in
   ``ops/groupby.py`` / ``ops/sort.py`` / ``ops/structural.py`` /
   ``ops/reductions.py`` was invisible: a "12.4 GB moved" number said
   nothing about how much of it was arithmetic on pad rows.  Padding sites
   call :func:`note_padding` (one ``COST_ON`` attribute check when off),
   which feeds ``engine.cost.padded_bytes`` / ``engine.cost.
   padding_waste_bytes`` counters, the per-thread counters EXPLAIN ANALYZE
   bills per plan node, and the Chrome-trace counter track.

Disabled-mode contract (the default): ``COST_ON`` is False unless
``MODIN_TPU_COST_CAPTURE`` is ``On``/``Full`` or (under ``Auto``) graftmeter
accounting is active; every instrumented site checks that ONE module
attribute and allocates nothing while it is False.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Tuple

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import spans as _spans

#: Module-level fast path, graftscope-style.  True while cost capture +
#: padding accounting are active: ``MODIN_TPU_COST_CAPTURE=On|Full``, or
#: ``Auto`` (the default) with graftmeter accounting live (meters on or an
#: open ``query_stats()`` scope).  Instrumented sites check this ONE
#: attribute before doing anything else.
COST_ON: bool = False

#: True only under ``MODIN_TPU_COST_CAPTURE=Full``: memory_analysis capture
#: pays a real AOT backend compile (listener-suppressed) per billed compile.
FULL_CAPTURE: bool = False

UNKNOWN = "unknown"

_mode = "Auto"

_tls = threading.local()

_pad_lock = named_lock("costs.padding")
# process-global padding accumulators (the Chrome counter track reads these)
_total_padded_bytes = 0
_total_waste_bytes = 0
# process-global collective-traffic accumulator (all_to_all / psum payload
# bytes observed at the instrumented collective sites, graftmesh)
_total_collective_bytes = 0
# most recent achieved bandwidth sample, bytes/s (Chrome counter track)
_last_achieved_bw = 0.0


# ---------------------------------------------------------------------- #
# enable/disable plumbing
# ---------------------------------------------------------------------- #


def _refresh() -> None:
    """Recompute the fast-path flags from the config knob + graftmeter."""
    global COST_ON, FULL_CAPTURE
    FULL_CAPTURE = _mode == "Full"
    if _mode == "Off":
        COST_ON = False
    elif _mode in ("On", "Full"):
        COST_ON = True
    else:  # Auto: piggyback on graftmeter accounting
        from modin_tpu.observability import meters as _meters

        COST_ON = _meters.ACCOUNTING_ON


def _on_cost_param(param: Any) -> None:
    global _mode
    _mode = str(param.get())
    _refresh()


def cost_capture_mode() -> str:
    return _mode


# ---------------------------------------------------------------------- #
# static cost extraction (graceful degradation is the whole point)
# ---------------------------------------------------------------------- #


def _first_mapping(analysis: Any) -> Optional[dict]:
    """jax's cost_analysis has returned a dict, a list of dicts, and None
    across versions; normalize to one mapping or None."""
    if isinstance(analysis, dict):
        return analysis
    if isinstance(analysis, (list, tuple)) and analysis:
        head = analysis[0]
        if isinstance(head, dict):
            return head
    return None


def extract_cost(analysis: Any) -> Dict[str, Any]:
    """``{"flops", "bytes_accessed", "transcendentals"}`` from a raw
    ``cost_analysis()`` result; every missing/absent value is ``"unknown"``.
    """
    mapping = _first_mapping(analysis) or {}

    def field(key: str) -> Any:
        value = mapping.get(key)
        if isinstance(value, (int, float)) and value >= 0:
            return float(value)
        return UNKNOWN

    return {
        "flops": field("flops"),
        "bytes_accessed": field("bytes accessed"),
        "transcendentals": field("transcendentals"),
    }


def extract_memory(stats: Any) -> Dict[str, Any]:
    """``{"argument_bytes", "output_bytes", "temp_bytes", "peak_bytes"}``
    from a ``memory_analysis()`` result; missing attributes -> ``"unknown"``.

    ``peak_bytes`` is the best-effort arg+out+temp sum when the backend
    reports no explicit peak (XLA:CPU reports component sizes only).
    """
    out: Dict[str, Any] = {}
    for field, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
    ):
        value = getattr(stats, attr, None)
        out[field] = (
            float(value) if isinstance(value, (int, float)) and value >= 0
            else UNKNOWN
        )
    peak = getattr(stats, "peak_memory_in_bytes", None)
    if isinstance(peak, (int, float)) and peak > 0:
        out["peak_bytes"] = float(peak)
    elif all(out[f] != UNKNOWN for f in ("argument_bytes", "output_bytes", "temp_bytes")):
        out["peak_bytes"] = (
            out["argument_bytes"] + out["output_bytes"] + out["temp_bytes"]
        )
    else:
        out["peak_bytes"] = UNKNOWN
    return out


_UNKNOWN_COST = {
    "flops": UNKNOWN,
    "bytes_accessed": UNKNOWN,
    "transcendentals": UNKNOWN,
}


def _merge_known(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
    """Overlay only the KNOWN fields of ``src`` — a later analysis that
    cannot answer a field must not clobber an earlier one that could."""
    for key, value in src.items():
        if value != UNKNOWN:
            dst[key] = value
        else:
            dst.setdefault(key, UNKNOWN)


def capture_static(func: Any, f_args: tuple, f_kwargs: Optional[dict]) -> Dict[str, Any]:
    """Best-effort static cost of the program ``func`` compiles to.

    Uses the AOT ``lower()`` path: ``Lowered.cost_analysis()`` answers from
    the unoptimized HLO without a backend compile (measured: no
    ``backend_compile_duration`` event fires).  Under ``Full`` mode the
    lowered program IS backend-compiled once more for ``memory_analysis()``
    — with the compile-ledger listener suppressed so the extra compile is
    never billed as workload.  Any failure anywhere yields unknown fields.
    """
    cost = dict(_UNKNOWN_COST)
    try:
        lower = getattr(func, "lower", None)
        if lower is None:
            return cost
        lowered = lower(*f_args, **(f_kwargs or {}))
        try:
            _merge_known(cost, extract_cost(lowered.cost_analysis()))
        except Exception:
            pass
        if FULL_CAPTURE:
            from modin_tpu.observability import compile_ledger as _ledger_mod

            with _ledger_mod.suppress_listener():
                compiled = lowered.compile()
            try:
                _merge_known(cost, extract_cost(compiled.cost_analysis()))
            except Exception:
                pass
            try:
                _merge_known(cost, extract_memory(compiled.memory_analysis()))
            except Exception:
                pass
    except Exception:
        # a broken capture must never break the dispatch it observes
        pass
    return cost


# ---------------------------------------------------------------------- #
# the cost ledger (per attribution signature)
# ---------------------------------------------------------------------- #


class CostLedger:
    """Thread-safe per-signature cost entries joined with dispatch wall."""

    def __init__(self) -> None:
        self._lock = named_lock("costs.ledger")
        self._entries: Dict[str, dict] = {}
        self._padding: Dict[str, dict] = {}  # per padding site
        self._collective: Dict[str, dict] = {}  # per collective site

    def _entry(self, signature: str) -> dict:
        entry = self._entries.get(signature)
        if entry is None:
            entry = self._entries[signature] = {
                "captures": 0,
                "flops": UNKNOWN,
                "bytes_accessed": UNKNOWN,
                "transcendentals": UNKNOWN,
                "dispatches": 0,
                "wall_s": 0.0,
                # accumulated per dispatch (the dispatch's OWN program
                # cost, not last-capture x count): one signature legally
                # pools many programs — or, untraced, every program
                "flops_total": 0.0,
                "bytes_total": 0.0,
            }
        return entry

    def record_capture(self, signature: str, cost: Dict[str, Any]) -> None:
        with self._lock:
            entry = self._entry(signature)
            entry["captures"] += 1
            _merge_known(entry, cost)

    def record_dispatch(
        self,
        signature: str,
        wall_s: float,
        flops: Any = UNKNOWN,
        bytes_accessed: Any = UNKNOWN,
    ) -> None:
        with self._lock:
            entry = self._entry(signature)
            entry["dispatches"] += 1
            entry["wall_s"] += wall_s
            if flops != UNKNOWN and flops is not None:
                entry["flops_total"] += flops
            if bytes_accessed != UNKNOWN and bytes_accessed is not None:
                entry["bytes_total"] += bytes_accessed

    def record_padding(self, site: str, padded_bytes: int, valid_bytes: int) -> None:
        with self._lock:
            entry = self._padding.get(site)
            if entry is None:
                entry = self._padding[site] = {
                    "events": 0, "padded_bytes": 0, "waste_bytes": 0,
                }
            entry["events"] += 1
            entry["padded_bytes"] += padded_bytes
            entry["waste_bytes"] += max(padded_bytes - valid_bytes, 0)

    def record_collective(self, site: str, nbytes: int) -> None:
        with self._lock:
            entry = self._collective.get(site)
            if entry is None:
                entry = self._collective[site] = {"events": 0, "bytes": 0}
            entry["events"] += 1
            entry["bytes"] += nbytes

    def efficiency(self, signature: str) -> Optional[dict]:
        """Achieved FLOP/s, bandwidth, and roofline fraction for one
        signature (None if never dispatched).  ``async_caveat`` is always
        True: the recorded wall is the engine-seam attempt wall, which on
        an async substrate is enqueue time (the post-deploy BenchmarkMode
        sync happens after the seam) — treat per-signature fractions as an
        upper bound and use the EXPLAIN ANALYZE per-node join (measured
        node wall, materialization included) for honest numbers."""
        with self._lock:
            entry = self._entries.get(signature)
            if entry is None or entry["dispatches"] == 0:
                return None
            entry = dict(entry)
        wall = entry["wall_s"]
        flops_total = entry["flops_total"]
        bytes_total = entry["bytes_total"]
        achieved_flops = (
            flops_total / wall if flops_total > 0 and wall > 0 else UNKNOWN
        )
        achieved_bw = (
            bytes_total / wall if bytes_total > 0 and wall > 0 else UNKNOWN
        )
        return {
            **entry,
            "achieved_flops_per_s": achieved_flops,
            "achieved_bytes_per_s": achieved_bw,
            "roofline_fraction": roofline_fraction(
                flops_total or None, bytes_total or None, wall
            ) or UNKNOWN,
            "async_caveat": True,
        }

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "signatures": {s: dict(e) for s, e in self._entries.items()},
                "padding": {s: dict(e) for s, e in self._padding.items()},
                "collective": {
                    s: dict(e) for s, e in self._collective.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._padding.clear()
            self._collective.clear()


_LEDGER = CostLedger()


def get_cost_ledger() -> CostLedger:
    return _LEDGER


def reset() -> None:
    """Clear the cost ledger and the process padding accumulators (tests,
    per-section bench resets)."""
    global _total_padded_bytes, _total_waste_bytes, _last_achieved_bw
    global _total_collective_bytes
    _LEDGER.reset()
    with _pad_lock:
        _total_padded_bytes = 0
        _total_waste_bytes = 0
        _total_collective_bytes = 0
        _last_achieved_bw = 0.0


# ---------------------------------------------------------------------- #
# per-thread counters (EXPLAIN ANALYZE takes deltas, like thread_dispatches)
# ---------------------------------------------------------------------- #


def thread_cost() -> Tuple[float, float]:
    """Monotonic per-thread (estimated flops, estimated bytes accessed)."""
    return (getattr(_tls, "flops", 0.0), getattr(_tls, "bytes", 0.0))


def thread_padding() -> Tuple[int, int]:
    """Monotonic per-thread (padded bytes, padding-waste bytes)."""
    return (getattr(_tls, "padded", 0), getattr(_tls, "waste", 0))


def thread_collective() -> int:
    """Monotonic per-thread collective-payload bytes (all_to_all/psum)."""
    return getattr(_tls, "collective", 0)


def _bump_thread_cost(flops: Any, bytes_accessed: Any) -> None:
    if flops != UNKNOWN and flops is not None:
        _tls.flops = getattr(_tls, "flops", 0.0) + flops
    if bytes_accessed != UNKNOWN and bytes_accessed is not None:
        _tls.bytes = getattr(_tls, "bytes", 0.0) + bytes_accessed


# ---------------------------------------------------------------------- #
# the deploy-seam hook
# ---------------------------------------------------------------------- #

#: per-jitted-function cost memo: a warm dispatch (no compile billed)
#: re-bills the costs captured at its compile so EXPLAIN ANALYZE and the
#: metric stream see estimated work on cache hits too.  Keyed weakly on the
#: function object (jitted callables are long-lived, cached per op family)
#: then by the argument shape/dtype key (one jit compiles per shape).
import weakref  # noqa: E402

_func_costs: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _arg_key(f_args: tuple, f_kwargs: Optional[dict]) -> tuple:
    """Shape/dtype fingerprint of a dispatch's full argument tree.

    Every input that changes which program jit compiles must land in the
    key: jax AND numpy arrays contribute (shape, dtype), hashable scalars
    their value (a different static scalar can mean a different program),
    kwargs are walked too.  Anything else falls back to its type name.
    """
    import jax
    import numpy as np

    key = []
    stack = [f_args]
    if f_kwargs:
        stack.append(tuple(sorted(f_kwargs.items())))
    while stack:
        item = stack.pop()
        if isinstance(item, (tuple, list)):
            stack.extend(item)
        elif isinstance(item, dict):
            stack.extend(sorted(item.items()))
        elif isinstance(item, (jax.Array, np.ndarray)):
            key.append((tuple(item.shape), str(item.dtype)))
        elif isinstance(item, (int, float, bool, str, bytes, type(None))):
            key.append((type(item).__name__, item))
        else:
            key.append(type(item).__name__)
    return tuple(key)


def dispatch_recorder(func: Any, f_args: tuple, f_kwargs: Optional[dict]):
    """One-dispatch cost hook for ``engine_call`` (built in ``deploy``).

    The returned callable runs on the dispatching thread right after a
    successful deploy attempt, while the ``engine.<op>.attempt`` span is
    still open: a billed compile triggers a fresh static capture (memoized
    per (func, argument shapes/dtypes)); a cache hit re-bills the memoized
    costs.  Either way the costs land on the attempt span, the metric
    stream, the per-thread counters, and the cost ledger joined with the
    wall of the SUCCESSFUL attempt (``engine_call`` times each attempt, so
    retries and backoff sleeps are never billed as dispatch wall; the
    recorder's own clock is only the fallback).
    """
    t0 = time.perf_counter()

    def record(compiled: bool, sp: Any, attempt_wall_s: Optional[float] = None) -> None:
        global _last_achieved_bw
        try:
            key = None
            cost = None
            try:
                key = _arg_key(f_args, f_kwargs)
                per_func = _func_costs.get(func)
            except TypeError:  # unhashable/unweakrefable func
                per_func = None
            if not compiled and per_func is not None:
                cost = per_func.get(key)
            if cost is None:
                cost = capture_static(func, f_args, f_kwargs)
                if key is not None:
                    try:
                        if per_func is None:
                            per_func = _func_costs.setdefault(func, {})
                        per_func[key] = cost
                    except TypeError:
                        pass
            wall_s = (
                attempt_wall_s
                if attempt_wall_s is not None
                else time.perf_counter() - t0
            )
            signature = _spans.attribution_signature()
            flops = cost.get("flops", UNKNOWN)
            bytes_acc = cost.get("bytes_accessed", UNKNOWN)
            transc = cost.get("transcendentals", UNKNOWN)
            peak = cost.get("peak_bytes", UNKNOWN)
            if compiled:
                _LEDGER.record_capture(signature, cost)
                # the compile ledger's per-signature entry carries the
                # static costs too: one snapshot answers "who compiled,
                # how often, and what does the program cost"
                from modin_tpu.observability.compile_ledger import (
                    get_compile_ledger,
                )

                get_compile_ledger().record_cost(signature, cost)
            # the dispatch's OWN program cost accumulates (a signature can
            # pool several programs; last-capture x count would be wrong)
            _LEDGER.record_dispatch(signature, wall_s, flops, bytes_acc)
            _bump_thread_cost(flops, bytes_acc)
            if flops != UNKNOWN:
                emit_metric("engine.cost.flops", flops)
            if bytes_acc != UNKNOWN:
                emit_metric("engine.cost.bytes", bytes_acc)
                if wall_s > 0:
                    _last_achieved_bw = bytes_acc / wall_s
            if transc != UNKNOWN and transc > 0:
                emit_metric("engine.cost.transcendentals", transc)
            if peak != UNKNOWN:
                emit_metric("engine.cost.peak_bytes", peak)
            if sp is not None:
                sp.attrs["cost_flops"] = flops
                sp.attrs["cost_bytes"] = bytes_acc
                if peak != UNKNOWN:
                    sp.attrs["cost_peak_bytes"] = peak
        except Exception:
            # accounting must never break the dispatch it measures
            pass

    return record


# ---------------------------------------------------------------------- #
# padding-waste accounting
# ---------------------------------------------------------------------- #


def note_padding(site: str, padded_bytes: int, valid_bytes: int) -> None:
    """One padded device allocation/move: ``padded_bytes`` physical vs
    ``valid_bytes`` logical.  Call sites gate on :data:`COST_ON`; the
    difference is billed as padding waste to the metric stream, the
    per-thread counters, the per-site ledger, and the Chrome counter track.
    Zero waste (already aligned) is still recorded — "no padding" is an
    answer too.
    """
    global _total_padded_bytes, _total_waste_bytes
    try:
        padded_bytes = int(padded_bytes)
        waste = max(padded_bytes - int(valid_bytes), 0)
        _tls.padded = getattr(_tls, "padded", 0) + padded_bytes
        _tls.waste = getattr(_tls, "waste", 0) + waste
        with _pad_lock:
            _total_padded_bytes += padded_bytes
            _total_waste_bytes += waste
        _LEDGER.record_padding(site, padded_bytes, int(valid_bytes))
        emit_metric("engine.cost.padded_bytes", padded_bytes)
        emit_metric("engine.cost.padding_waste_bytes", waste)
        if _spans.TRACE_ON:
            sp = _spans.current_span()
            if sp is not None:
                sp.attrs["padding_waste_bytes"] = (
                    sp.attrs.get("padding_waste_bytes", 0) + waste
                )
    except Exception:
        pass


def note_collective(site: str, nbytes: int) -> None:
    """One collective payload crossing the interconnect: ``nbytes`` moved
    through an all_to_all/psum at ``site``.  Call sites gate on
    :data:`COST_ON`.  Feeds ``engine.cost.collective_bytes``, the
    per-thread counter, and the per-site ledger — the observability leg of
    the router's collective-aware crossover model (graftmesh).
    """
    global _total_collective_bytes
    try:
        nbytes = int(nbytes)
        _tls.collective = getattr(_tls, "collective", 0) + nbytes
        with _pad_lock:
            _total_collective_bytes += nbytes
        _LEDGER.record_collective(site, nbytes)
        emit_metric("engine.cost.collective_bytes", nbytes)
        if _spans.TRACE_ON:
            sp = _spans.current_span()
            if sp is not None:
                sp.attrs["collective_bytes"] = (
                    sp.attrs.get("collective_bytes", 0) + nbytes
                )
    except Exception:
        pass


def counter_sample() -> tuple:
    """(total padding-waste bytes, last achieved bandwidth bytes/s) — the
    two graftcost Chrome-trace counter tracks, sampled at span finish."""
    return (_total_waste_bytes, int(_last_achieved_bw))


# ---------------------------------------------------------------------- #
# roofline peaks
# ---------------------------------------------------------------------- #

#: peak (FLOP/s, bytes/s) per accelerator device kind — published spec
#: sheets (f32 dense for flops, HBM bandwidth).  A kind not listed falls
#: back to the measured micro-benchmark below.
KNOWN_PEAKS: Dict[str, Tuple[float, float]] = {
    "TPU v2": (45e12, 0.7e12),
    "TPU v3": (123e12, 0.9e12),
    "TPU v4": (275e12, 1.2e12),
    "TPU v5 lite": (197e12, 0.82e12),
    "TPU v5e": (197e12, 0.82e12),
    "TPU v5p": (459e12, 2.76e12),
    "TPU v6e": (918e12, 1.64e12),
}

_peaks_cache: Optional[dict] = None
_peaks_lock = named_lock("costs.peaks")


def _measure_host_peaks() -> Optional[dict]:
    """One-shot micro-benchmark of this host: dense-dot FLOP/s and memcpy
    bandwidth via numpy.  ~100ms once per substrate; cached to CacheDir."""
    import numpy as np

    try:
        k = 512
        a = np.random.default_rng(0).random((k, k))
        b = np.random.default_rng(1).random((k, k))
        a @ b  # warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            a @ b
            best = min(best, time.perf_counter() - t0)
        flops = 2.0 * k**3 / max(best, 1e-9)
        src = np.zeros(8 << 20, dtype=np.int8)  # 8 MiB
        np.copyto(np.empty_like(src), src)  # warm
        best_bw = float("inf")
        for _ in range(3):
            dst = np.empty_like(src)
            t0 = time.perf_counter()
            np.copyto(dst, src)
            best_bw = min(best_bw, time.perf_counter() - t0)
        bw = 2.0 * src.nbytes / max(best_bw, 1e-9)  # read + write
        return {"flops_per_s": flops, "bytes_per_s": bw, "source": "measured"}
    except Exception:
        return None


def substrate_peaks() -> Optional[dict]:
    """Peak FLOP/s + memory bandwidth of the current substrate, or None.

    Known accelerator kinds answer from :data:`KNOWN_PEAKS`; anything else
    (XLA:CPU included) is measured once by a tiny numpy micro-benchmark and
    cached to ``MODIN_TPU_CACHE_DIR`` per platform so later processes skip
    the measurement.  None means "no basis for a roofline" — consumers
    render the fraction as unknown rather than invent one.
    """
    global _peaks_cache
    if _peaks_cache is not None:
        return _peaks_cache or None
    with _peaks_lock:
        if _peaks_cache is not None:
            return _peaks_cache or None
        peaks: Optional[dict] = None
        platform = "unknown"
        try:
            import jax

            device = jax.devices()[0]
            platform = device.platform
            kind = getattr(device, "device_kind", "")
            for known, (flops, bw) in KNOWN_PEAKS.items():
                if kind and known.lower() in str(kind).lower():
                    peaks = {
                        "flops_per_s": flops,
                        "bytes_per_s": bw,
                        "source": f"spec:{known}",
                    }
                    break
        except Exception:
            pass
        if peaks is None:
            peaks = _load_cached_peaks(platform)
        if peaks is None:
            peaks = _measure_host_peaks()
            if peaks is not None:
                _store_cached_peaks(platform, peaks)
        _peaks_cache = peaks if peaks is not None else {}
        return peaks


def _peaks_path(platform: str) -> Optional[str]:
    # persistence rides the consolidated calibration store (ops/
    # calibration.py); the name stays byte-compatible with the
    # pre-consolidation layout so warmed caches survive the refactor
    from modin_tpu.ops import calibration as calstore

    return calstore.table_path("roofline", platform)


def _load_cached_peaks(platform: str) -> Optional[dict]:
    from modin_tpu.ops import calibration as calstore

    peaks = calstore.load_table(_peaks_path(platform))
    if (
        isinstance(peaks, dict)
        and peaks.get("flops_per_s", 0) > 0
        and peaks.get("bytes_per_s", 0) > 0
    ):
        return peaks
    return None


def _store_cached_peaks(platform: str, peaks: dict) -> None:
    from modin_tpu.ops import calibration as calstore

    calstore.store_table(_peaks_path(platform), peaks)


def roofline_fraction(
    flops: Optional[float], bytes_accessed: Optional[float], wall_s: float
) -> Optional[float]:
    """Achieved fraction of the roofline-attainable rate for this program.

    ``min(peak_flops, intensity * peak_bw)`` is the classic attainable
    ceiling at the program's arithmetic intensity; the fraction is achieved
    FLOP/s over that.  For a pure-movement program (zero flops) the
    fraction is achieved bandwidth over peak bandwidth.  None when wall or
    the needed estimates are unknown.
    """
    if wall_s <= 0:
        return None
    peaks = substrate_peaks()
    if peaks is None:
        return None
    peak_flops = peaks["flops_per_s"]
    peak_bw = peaks["bytes_per_s"]
    if flops is not None and flops > 0:
        if bytes_accessed is not None and bytes_accessed > 0:
            intensity = flops / bytes_accessed
            attainable = min(peak_flops, intensity * peak_bw)
        else:
            attainable = peak_flops
        return (flops / wall_s) / attainable
    if bytes_accessed is not None and bytes_accessed > 0:
        return (bytes_accessed / wall_s) / peak_bw
    return None


# wire the config switch (fires immediately with its current value)
from modin_tpu.config import CostCapture as _CostCapture  # noqa: E402

_CostCapture.subscribe(_on_cost_param)
