"""graftwatch per-tenant SLO tracking: latency objectives and burn rates.

An SLO here is "at least :data:`TARGET_FRACTION` of a tenant's admitted
queries finish under their objective latency" — objectives come from
``MODIN_TPU_WATCH_SLO_MS`` (``"default=250,alice=50"``; a bare number is
shorthand for ``default=``).  The serving gate feeds every finished
query's ``(tenant, wall_s)`` through ``watch.observe_query`` (one
module-attribute check when watch is off), and this tracker answers the
operator question the raw histogram cannot: *how fast is each tenant
burning its error budget right now?*

Burn rate is the standard SRE multi-window form: over a window,
``burn = bad_fraction / (1 - TARGET_FRACTION)`` — 1.0 means the tenant is
spending budget exactly as fast as the SLO allows, >1 means faster.  Two
windows are computed (:data:`FAST_WINDOW_S` / :data:`SLOW_WINDOW_S`);
"breaching" requires BOTH above 1.0 with at least :data:`MIN_SAMPLES`
fast-window observations, so one unlucky query never pages and a
recovered incident stops paging as soon as the fast window clears.  The
verdict is *advisory*: graftgate surfaces it in ``serving_snapshot()``
next to the breaker states, and the ``slo_burn`` tripwire captures
evidence — nothing is shed because of it.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.observability.watch.timeseries import note_alloc

#: fraction of queries that must meet the objective (the error budget is
#: ``1 - TARGET_FRACTION``); module-level so tests can tighten it
TARGET_FRACTION = 0.99

#: the two burn windows (seconds); module-level so tests and the smoke
#: gate can shrink them instead of sleeping real minutes
FAST_WINDOW_S = 60.0
SLOW_WINDOW_S = 300.0

#: minimum fast-window observations before a breach verdict is possible
MIN_SAMPLES = 4

#: per-tenant observation ring capacity and the tenant cardinality cap
#: (mirrors serving/tenants.py: per-user tenant ids must not grow memory;
#: like there, the cap LRU-EVICTS the least-recently-observed tenant —
#: permanently ignoring every tenant created after the first 1024 would
#: blind SLO tracking to exactly the churn the cap exists to survive)
_MAX_OBSERVATIONS = 4096
_MAX_TENANTS = 1024


def parse_slo_ms(spec: str) -> Dict[str, float]:
    """``"default=250,alice=50"`` -> {"default": 0.25, "alice": 0.05}
    (values in SECONDS).  A bare number is ``default=``; malformed or
    non-positive entries are skipped — config must never crash telemetry.
    """
    objectives: Dict[str, float] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, value = part.partition("=")
            name = name.strip()
        else:
            name, value = "default", part
        try:
            ms = float(value)
        except ValueError:
            continue
        if ms > 0 and name:
            objectives[name] = ms / 1e3
    return objectives


class SloTracker:
    """Thread-safe per-tenant latency observations + burn-rate math."""

    def __init__(self) -> None:
        note_alloc()
        self._lock = named_lock("watch.slo")
        self._observations: "OrderedDict[str, deque]" = OrderedDict()
        self.evicted_tenants = 0

    def _objectives(self) -> Dict[str, float]:
        from modin_tpu.config import WatchSloMs

        return parse_slo_ms(WatchSloMs.get())

    def observe(
        self, tenant: str, wall_s: float, now: Optional[float] = None
    ) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            ring = self._observations.get(tenant)
            if ring is None:
                while len(self._observations) >= _MAX_TENANTS:
                    self._observations.popitem(last=False)  # LRU tenant
                    self.evicted_tenants += 1
                ring = self._observations[tenant] = deque(
                    maxlen=_MAX_OBSERVATIONS
                )
            else:
                self._observations.move_to_end(tenant)
            # age-prune on the write path: nothing reads past the slow
            # window, and health() copies each ring under this same lock
            # every sampler tick — retaining up to 4096 stale samples per
            # tenant would make the serving hot path (observe blocks on
            # the lock) pay for history no verdict can use
            horizon = now - SLOW_WINDOW_S
            while ring and ring[0][0] < horizon:
                ring.popleft()
            ring.append((now, float(wall_s)))

    def objective_s(self, tenant: str) -> Optional[float]:
        """The tenant's objective in seconds (its own entry, else the
        ``default`` entry), or None when untracked."""
        objectives = self._objectives()
        return objectives.get(tenant, objectives.get("default"))

    @staticmethod
    def _burn(
        window: list, objective_s: float
    ) -> Optional[float]:
        if not window:
            return None
        bad = sum(1 for _t, wall in window if wall > objective_s)
        budget = max(1.0 - TARGET_FRACTION, 1e-9)
        return (bad / len(window)) / budget

    def health(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-tenant burn verdicts for every OBSERVED tenant that has an
        objective.  ``breaching`` is the advisory multi-window verdict."""
        now = time.monotonic() if now is None else now
        objectives = self._objectives()
        if not objectives:
            return {}
        with self._lock:
            observed = {
                tenant: list(ring)
                for tenant, ring in self._observations.items()
            }
        out: Dict[str, dict] = {}
        for tenant, obs in sorted(observed.items()):
            objective = objectives.get(tenant, objectives.get("default"))
            if objective is None:
                continue
            fast = [s for s in obs if s[0] >= now - FAST_WINDOW_S]
            slow = [s for s in obs if s[0] >= now - SLOW_WINDOW_S]
            fast_burn = self._burn(fast, objective)
            slow_burn = self._burn(slow, objective)
            breaching = bool(
                fast_burn is not None
                and slow_burn is not None
                and len(fast) >= MIN_SAMPLES
                and fast_burn > 1.0
                and slow_burn > 1.0
            )
            out[tenant] = {
                "objective_ms": round(objective * 1e3, 3),
                "target": TARGET_FRACTION,
                "fast_window_s": FAST_WINDOW_S,
                "slow_window_s": SLOW_WINDOW_S,
                "fast_burn": (
                    round(fast_burn, 3) if fast_burn is not None else None
                ),
                "slow_burn": (
                    round(slow_burn, 3) if slow_burn is not None else None
                ),
                "fast_samples": len(fast),
                "breaching": breaching,
            }
        return out

    def breaching(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Just the tenants currently breaching (the slo_burn tripwire)."""
        return {
            tenant: verdict
            for tenant, verdict in self.health(now).items()
            if verdict["breaching"]
        }

    def latency_stats(self, now: Optional[float] = None) -> Dict[str, dict]:
        """Per-tenant fast-window p50/p99/count for ``/statusz`` — every
        observed tenant, objective or not."""
        now = time.monotonic() if now is None else now
        with self._lock:
            observed = {
                tenant: [
                    wall
                    for t, wall in ring
                    if t >= now - FAST_WINDOW_S
                ]
                for tenant, ring in self._observations.items()
            }
        out: Dict[str, dict] = {}
        for tenant, walls in sorted(observed.items()):
            if not walls:
                continue
            ordered = sorted(walls)

            def pick(q: float) -> float:
                idx = min(int(q * (len(ordered) - 1) + 0.5), len(ordered) - 1)
                return ordered[idx]

            out[tenant] = {
                "count": len(ordered),
                "p50_ms": round(pick(0.50) * 1e3, 3),
                "p99_ms": round(pick(0.99) * 1e3, 3),
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._observations.clear()
            self.evicted_tenants = 0
