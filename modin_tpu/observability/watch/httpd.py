"""graftwatch live exporter: a stdlib HTTP thread over the telemetry state.

One ``http.server.ThreadingHTTPServer`` bound to ``127.0.0.1`` on
``MODIN_TPU_WATCH_PORT`` (0 = OS-assigned ephemeral; the live port reads
back via ``watch.httpd_port()``), serving:

- ``GET /metrics`` — the meter registry as Prometheus text exposition
  (``observability/exposition.py``; the same text the smoke gates
  validate with ``parse_prometheus``), scrapeable by a real collector;
- ``GET /statusz`` — a human-readable one-page status: uptime, sampler
  health, mesh shape, ledger residency, admission-gate pressure,
  windowed rates/quantiles off the rings, per-tenant table with SLO
  burn rates, recent tripwires;
- ``GET /debug/queries`` — the live ``query_stats()`` scopes process-wide
  (graftmeter's open-scope registry) as JSON, wall-so-far included;
- ``GET /`` — a plain-text index of the above.

Every request emits one ``watch.scrape``.  Handlers never raise into the
socket loop and never write to stderr (``log_message`` is silenced); an
endpoint whose renderer fails returns 500 with the error name rather
than killing the exporter thread.
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_INDEX = (
    "graftwatch live exporter\n"
    "  /metrics        Prometheus text exposition of the meter registry\n"
    "  /statusz        human-readable service status\n"
    "  /debug/queries  live query_stats scopes (JSON)\n"
)


class _WatchHandler(BaseHTTPRequestHandler):
    server_version = "modin-tpu-graftwatch"

    def log_message(self, fmt: str, *args) -> None:  # noqa: D102
        pass  # telemetry must never spam the host application's stderr

    def _respond(
        self, status: int, content_type: str, body: str
    ) -> None:
        payload = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
        try:
            from modin_tpu.logging.metrics import emit_metric

            emit_metric("watch.scrape", 1)
        except Exception:
            pass
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                from modin_tpu.observability import exposition, meters

                self._respond(
                    200,
                    "text/plain; version=0.0.4; charset=utf-8",
                    exposition.to_prometheus(meters.snapshot()),
                )
            elif path == "/statusz":
                service = self.server.watch_service  # type: ignore[attr-defined]
                self._respond(
                    200, "text/plain; charset=utf-8", service.statusz_text()
                )
            elif path == "/debug/queries":
                self._respond(
                    200,
                    "application/json; charset=utf-8",
                    json.dumps(_debug_queries(), sort_keys=True),
                )
            elif path == "/":
                self._respond(200, "text/plain; charset=utf-8", _INDEX)
            else:
                self._respond(
                    404, "text/plain; charset=utf-8", f"unknown path {path}\n"
                )
        except BrokenPipeError:
            pass  # the scraper hung up; nothing to salvage
        except Exception as err:
            try:
                self._respond(
                    500,
                    "text/plain; charset=utf-8",
                    f"renderer failed: {type(err).__name__}: {err}\n",
                )
            except Exception:
                pass


def _debug_queries() -> dict:
    from modin_tpu.observability import meters

    queries = []
    for qs in meters.live_scopes():
        entry = qs.as_dict()
        entry["wall_so_far_s"] = round(qs.elapsed_s(), 6)
        entry["open"] = not qs._closed
        queries.append(entry)
    return {"open_scopes": len(queries), "queries": queries}


class Exporter:
    """Lifecycle wrapper around the exporter server + its serve thread."""

    THREAD_NAME = "modin-tpu-watch-httpd"

    def __init__(self, service) -> None:
        self._service = service
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        server = self._server
        return server.server_address[1] if server is not None else None

    def start(self, port: int) -> bool:
        """Bind 127.0.0.1:port (0 = ephemeral) and serve on a daemon
        thread.  Returns False (service keeps running exporter-less) when
        the bind fails — a taken port must not take queries down."""
        if self._server is not None:
            return True
        try:
            server = ThreadingHTTPServer(
                ("127.0.0.1", max(port, 0)), _WatchHandler
            )
        except Exception as err:
            # not just OSError: an env-sourced out-of-range port (which
            # bypasses WatchPort.put validation) raises OverflowError
            # from bind() — any bind failure degrades exporter-less
            print(
                f"graftwatch: exporter bind failed on port {port}: {err}; "
                "rings/SLO/tripwires keep running without HTTP",
                file=sys.stderr,
            )
            return False
        server.daemon_threads = True
        server.watch_service = self._service  # type: ignore[attr-defined]
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever,
            name=self.THREAD_NAME,
            daemon=True,
            kwargs={"poll_interval": 0.1},
        )
        self._thread.start()
        return True

    def stop(self, timeout: float = 5.0) -> None:
        server = self._server
        if server is None:
            return
        self._server = None
        try:
            server.shutdown()
            server.server_close()
        except Exception:
            pass
        thread = self._thread
        self._thread = None
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()
