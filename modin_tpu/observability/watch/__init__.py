"""graftwatch — always-on serving telemetry for an operable graftgate.

Every observability surface before this one (graftscope traces,
graftmeter snapshots / EXPLAIN ANALYZE, graftcost rooflines) is
pull-on-demand from inside the process: an operator cannot watch p99
drift, spill thrash, or a recompile storm *while* the gate is shedding,
and the flight recorder only dumps after a breaker already opened.
graftwatch is the background service that closes that gap — four legs:

1. **time-series rings** (watch/timeseries.py): a sampler thread folds
   the meter registry, device/host ledger gauges, admission-gate depth,
   and compile-ledger totals into bounded rings every
   ``MODIN_TPU_WATCH_INTERVAL_S``, making "p99 over the last 60s" and
   "spill bytes/s" answerable questions;
2. **live exporter** (watch/httpd.py): ``/metrics`` (Prometheus text),
   ``/statusz`` (human one-pager), ``/debug/queries`` (live query
   scopes) on ``MODIN_TPU_WATCH_PORT``;
3. **per-tenant SLO burn rates** (watch/slo.py): objectives from
   ``MODIN_TPU_WATCH_SLO_MS``, fed per query by the serving gate,
   multi-window fast/slow burn surfaced to graftgate as an ADVISORY
   health signal next to the breakers;
4. **anomaly tripwires** (watch/tripwires.py): declarative rules over
   the rings that emit ``watch.trip.<rule>`` and auto-capture a
   rate-limited evidence bundle to ``MODIN_TPU_TRACE_DIR``.

Zero-overhead-when-off (the default, ``MODIN_TPU_WATCH=0``): no sampler
or exporter thread exists, the serving gate's per-query hook costs one
module-attribute check of :data:`WATCH_ON`, and nothing is allocated —
:func:`watch_alloc_count` asserts it exactly the way
``spans.span_alloc_count()`` asserts the tracing contract.  A sampler
crash emits ``watch.sampler.died`` and degrades the service to disabled
instead of taking queries down.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional

from modin_tpu.concurrency import named_rlock
from modin_tpu.observability.watch.timeseries import (  # noqa: F401
    Ring,
    RingStore,
    Sampler,
    alloc_count as _ts_alloc_count,
)

#: Module-level fast path, graftscope-style: the ONE attribute hot-path
#: hooks (the serving gate's per-query SLO observation) check before
#: doing anything else.  True only while the service is running.
WATCH_ON: bool = False

_state_lock = named_rlock("watch.state")
_service: Optional["WatchService"] = None
_env_enabled = False


def watch_alloc_count() -> int:
    """graftwatch objects ever constructed (rings, trackers, tripwires,
    samplers) — the zero-overhead-when-off assertion counter."""
    return _ts_alloc_count()


class WatchService:
    """The running telemetry service: rings + sampler + SLO + tripwires +
    exporter, one instance while ``MODIN_TPU_WATCH=1``."""

    def __init__(self) -> None:
        from modin_tpu.observability.watch.slo import SloTracker
        from modin_tpu.observability.watch.tripwires import TripwireEngine

        self.rings = RingStore()
        self.slo = SloTracker()
        self.tripwires = TripwireEngine(self)
        self.sampler = Sampler(
            self.rings,
            on_tick=self.tripwires.on_tick,
            on_died=self._on_sampler_died,
        )
        from modin_tpu.observability.watch.httpd import Exporter

        self.exporter = Exporter(self)
        self.started_monotonic: Optional[float] = None
        self.started_wall: Optional[float] = None
        self._registry_hold = False  # one acquire_registry per service run

    # -- lifecycle ------------------------------------------------------- #

    def start(self) -> None:
        """Start sampler + exporter (idempotent)."""
        if self.started_monotonic is None:
            self.started_monotonic = time.monotonic()
            self.started_wall = time.time()
        if not self._registry_hold:
            # watch standalone must actually see series: hold registry
            # aggregation for the service's lifetime, independent of the
            # MODIN_TPU_METERS knob (the rings, /metrics, and every
            # registry-fed tripwire are dead without it)
            from modin_tpu.observability import meters as _meters

            _meters.acquire_registry()
            self._registry_hold = True
        self.sampler.start()
        from modin_tpu.config import WatchPort

        port = int(WatchPort.get())
        if port >= 0:
            self.exporter.start(port)

    def _release_registry(self) -> None:
        if self._registry_hold:
            self._registry_hold = False
            from modin_tpu.observability import meters as _meters

            _meters.release_registry()

    def stop(self) -> None:
        """Stop sampler + exporter (idempotent; state stays inspectable)."""
        self.sampler.stop()
        self.exporter.stop()
        self._release_registry()

    def _on_sampler_died(self, _err: BaseException) -> None:
        """The sampler loop crashed: degrade to disabled — flip the fast
        path off and stop the exporter, but never join the dying thread
        (it is the caller)."""
        global WATCH_ON
        with _state_lock:
            if self.sampler._thread is not threading.current_thread():
                # stale crash: a stop()/restart raced this callback (the
                # _run-side guard passed before the swap) — the current
                # state belongs to the new run, leave it alone
                return
            WATCH_ON = False
            self.exporter.stop()
            self._release_registry()

    # -- statusz --------------------------------------------------------- #

    def statusz_text(self) -> str:
        """The human-readable one-pager.  Every section is exception-
        isolated: a broken seam renders as an error line, never a 500."""
        lines: List[str] = ["graftwatch /statusz", ""]

        def section(title: str, render) -> None:
            lines.append(f"== {title} ==")
            try:
                render()
            except Exception as err:
                lines.append(f"  <unavailable: {type(err).__name__}: {err}>")
            lines.append("")

        def _service_section() -> None:
            uptime = (
                time.monotonic() - self.started_monotonic
                if self.started_monotonic is not None
                else 0.0
            )
            sampler = self.sampler
            age = (
                time.monotonic() - sampler.last_tick_t
                if sampler.last_tick_t is not None
                else None
            )
            lines.append(f"  pid: {os.getpid()}  uptime: {uptime:.1f}s")
            age_txt = f"{age:.1f}" if age is not None else "?"
            lines.append(
                f"  sampler: ticks={sampler.ticks} last_tick_age_s={age_txt}"
            )
            if sampler.died:
                lines.append(f"  sampler DIED: {sampler.error}")
            lines.append(
                f"  rings: {len(self.rings)} series "
                f"(dropped={self.rings.dropped_series})"
            )
            port = self.exporter.port
            lines.append(f"  exporter: 127.0.0.1:{port}")

        def _substrate_section() -> None:
            import sys as _sys

            mesh = _sys.modules.get("modin_tpu.parallel.mesh")
            shape = (
                mesh.mesh_shape_key() if mesh is not None else "uninitialized"
            )
            lines.append(f"  mesh shape: {shape}")
            from modin_tpu.observability import spans as _spans

            device_bytes, host_bytes = _spans._ledger_bytes()
            lines.append(
                f"  ledger: device_resident={device_bytes}B "
                f"host_cache={host_bytes}B"
            )

        def _rates_section() -> None:
            window = 60.0

            def fmt(value: Optional[float], unit: str) -> str:
                return f"{value:.3g}{unit}" if value is not None else "?"

            lines.append(
                f"  (trailing {window:g}s)  "
                f"dispatch/s: {fmt(self.rings.rate('engine.dispatch', window), '')}  "
                f"spill B/s: {fmt(self.rings.rate('memory.device.spill_bytes', window), '')}  "
                f"compiles: {fmt(self.rings.delta('compile.total', window), '')}"
            )
            p50 = self.rings.quantile("serving.query_wall_s", 0.50, window)
            p99 = self.rings.quantile("serving.query_wall_s", 0.99, window)
            lines.append(
                "  query wall: "
                f"p50={fmt(p50 * 1e3 if p50 is not None else None, 'ms')} "
                f"p99={fmt(p99 * 1e3 if p99 is not None else None, 'ms')}"
            )

        def _gate_section() -> None:
            import sys as _sys

            gate_mod = _sys.modules.get("modin_tpu.serving.gate")
            if gate_mod is None:
                lines.append("  serving not active in this process")
                return
            snap = gate_mod.gate.snapshot()
            lines.append(
                f"  running={snap['running']}/{snap['max_concurrent']} "
                f"queued={snap['queued']}/{snap['queue_depth']} "
                f"admitted={snap['admitted']} shed={snap['shed']} "
                f"degraded={snap['degraded']}"
            )

        def _tenants_section() -> None:
            import sys as _sys

            tenants_mod = _sys.modules.get("modin_tpu.serving.tenants")
            tenant_rows = (
                tenants_mod.registry.snapshot() if tenants_mod else {}
            )
            health = self.slo.health()
            stats = self.slo.latency_stats()
            names = sorted(set(tenant_rows) | set(health) | set(stats))
            if not names:
                lines.append("  no tenants observed")
                return
            lines.append(
                "  tenant | in_flight | admitted | shed | breaker | "
                "p50/p99 (60s) | slo fast/slow burn"
            )
            for name in names:
                row = tenant_rows.get(name, {})
                st = stats.get(name, {})
                verdict = health.get(name)
                latency = (
                    f"{st.get('p50_ms', '?')}/{st.get('p99_ms', '?')}ms"
                    if st
                    else "?"
                )
                slo_txt = "-"
                if verdict is not None:
                    slo_txt = (
                        f"{verdict['fast_burn']}/{verdict['slow_burn']}"
                        + (" BREACHING" if verdict["breaching"] else "")
                    )
                lines.append(
                    f"  {name} | {row.get('in_flight', 0)} | "
                    f"{row.get('admitted', 0)} | {row.get('shed', 0)} | "
                    f"{row.get('breaker', '?')} | {latency} | {slo_txt}"
                )

        def _fleet_section() -> None:
            import sys as _sys

            fleet_mod = _sys.modules.get("modin_tpu.fleet")
            if fleet_mod is None or not fleet_mod.FLEET_ON:
                lines.append("  fleet not active in this process")
                return
            coordinator = fleet_mod.get_coordinator()
            if coordinator is None:
                lines.append(
                    "  fleet enabled, no coordinator here (replica process)"
                )
                return
            snap = coordinator.snapshot()
            lines.append(
                "  replica | state | gen | pid | rpc_port | watch_port | "
                "tenants | in_flight | shed_rate | p50/p99 (ms)"
            )
            for row in snap["replicas"]:
                latency = (
                    f"{row['p50_ms']:.1f}/{row['p99_ms']:.1f}"
                    if row["p50_ms"] is not None
                    else "?"
                )
                lines.append(
                    f"  {row['index']} | {row['state']} | {row['generation']}"
                    f" | {row['pid']} | {row['rpc_port']} | "
                    f"{row['watch_port']} | {row['tenants']} | "
                    f"{row['in_flight']} | {row['shed_rate']:.2f}/s | "
                    f"{latency}"
                )
            lines.append(
                f"  routed={snap['routed']} "
                f"redispatched={snap['redispatched']} "
                f"lost={snap['lost']} respawned={snap['respawned']} "
                f"tenants_redistributed={snap['redistributed']}"
            )

        def _trips_section() -> None:
            recent = self.tripwires.snapshot()
            if not recent:
                lines.append("  none")
                return
            for trip in recent[-10:]:
                lines.append(
                    f"  [{trip['at_unix_s']}] {trip['rule']}: "
                    f"{trip['detail']}  evidence={trip['evidence']}"
                )

        section("service", _service_section)
        section("substrate", _substrate_section)
        section("windowed rates", _rates_section)
        section("admission gate", _gate_section)
        section("tenants", _tenants_section)
        section("fleet", _fleet_section)
        section("recent tripwires", _trips_section)
        return "\n".join(lines)


# ---------------------------------------------------------------------- #
# module API (the names the rest of the system calls)
# ---------------------------------------------------------------------- #


def get_service() -> Optional[WatchService]:
    """The service instance (present once watch was ever enabled this
    process; its threads only run while :data:`WATCH_ON`)."""
    return _service


def observe_query(
    tenant: str, wall_s: float, failure_kind: Optional[str] = None
) -> None:
    """One finished serving query: feed the tenant's SLO observations.

    The serving gate checks :data:`WATCH_ON` before calling (the
    zero-overhead contract); this re-check only guards the teardown race.
    ``failure_kind`` rides for future rules; deadline aborts count as
    latency observations too — a query the deadline killed is exactly the
    latency signal the SLO exists to catch.
    """
    service = _service
    if service is None or not WATCH_ON:
        return
    try:
        service.slo.observe(tenant, wall_s)
    except Exception:
        pass


def observe_view_read(view_key: str, lag_s: float) -> None:
    """One graftfeed live-view read: feed the view's freshness into the
    SLO burn machinery under a synthetic ``view:<feed>/<view>`` tenant, so
    per-view staleness burn surfaces in ``/statusz`` and the ``slo_burn``
    verdicts exactly like per-tenant latency does.  Callers check
    :data:`WATCH_ON` first (the zero-overhead contract)."""
    service = _service
    if service is None or not WATCH_ON:
        return
    try:
        service.slo.observe(f"view:{view_key}", lag_s)
    except Exception:
        pass


def slo_health() -> Dict[str, dict]:
    """Per-tenant burn verdicts ({} while off/untracked) — the advisory
    signal graftgate surfaces next to its breakers."""
    service = _service
    if service is None:
        return {}
    try:
        return service.slo.health()
    except Exception:
        return {}


def httpd_port() -> Optional[int]:
    """The exporter's live TCP port, or None while it is not serving."""
    service = _service
    return service.exporter.port if service is not None else None


def recent_trips() -> List[dict]:
    service = _service
    return service.tripwires.snapshot() if service is not None else []


def watch_snapshot() -> Dict[str, Any]:
    """Service state for tests / dashboards."""
    service = _service
    if service is None:
        return {"enabled": WATCH_ON, "service": None}
    return {
        "enabled": WATCH_ON,
        "sampler": {
            "alive": service.sampler.is_alive(),
            "ticks": service.sampler.ticks,
            "died": service.sampler.died,
            "error": service.sampler.error,
        },
        "exporter_port": service.exporter.port,
        "ring_series": len(service.rings),
        "recent_trips": service.tripwires.snapshot(),
        "slo": slo_health(),
    }


# ---------------------------------------------------------------------- #
# config wiring
# ---------------------------------------------------------------------- #


def _start_locked() -> None:
    global _service, WATCH_ON
    if _service is None:
        _service = WatchService()
    _service.start()
    WATCH_ON = True


def _stop_locked() -> None:
    global WATCH_ON
    WATCH_ON = False
    if _service is not None:
        _service.stop()


def _on_watch_param(param: Any) -> None:
    global _env_enabled
    with _state_lock:
        _env_enabled = bool(param.get())
        if _env_enabled:
            _start_locked()
        else:
            _stop_locked()


from modin_tpu.config import WatchEnabled as _WatchEnabled  # noqa: E402

_WatchEnabled.subscribe(_on_watch_param)
