"""graftwatch anomaly tripwires: declarative rules over the rings.

A tripwire is a named predicate evaluated after every sampler tick; when
it fires it (1) emits ``watch.trip.<rule>``, (2) records itself in the
service's recent-trips ring (``/statusz``), and (3) auto-captures an
**evidence bundle** to ``MODIN_TPU_TRACE_DIR`` — the flight-recorder span
segment rendered as a chrome-trace object (empty when tracing is off),
the meter snapshot, a ring excerpt, and the SLO health table, all in one
JSON file.  Capture is rate-limited through the flight recorder's
claim-token window, so a flapping rule (or a tripwire racing a
breaker-open dump over the same incident) produces ONE artifact set, and
each rule additionally re-arms only after :data:`RULE_COOLDOWN_S`.

The default catalog (docs/observability.md holds the operator table):

- ``latency_shift`` — fast-window p99 of ``serving.query_wall_s`` shifted
  >= :data:`LATENCY_SHIFT_FACTOR`x above the immediately preceding
  window's p99 (both windows need :data:`LATENCY_MIN_SAMPLES` samples,
  and the shifted p99 must clear :data:`LATENCY_FLOOR_S` — idle-system
  microsecond jitter is not an incident);
- ``recompile_storm`` — the compile ledger's storm-signature count grew
  inside the window (shape/dtype churn defeating the executable cache);
- ``spill_thrash`` — >= :data:`SPILL_MIN_EVENTS` device spills in the
  window while cache hits (fused + sorted-rep + view) fell vs the
  previous window: the ledger is evicting the caches the workload is
  trying to use;
- ``shed_spike`` — >= :data:`SHED_MIN_EVENTS` typed sheds in the window;
- ``slo_burn`` — some tenant's multi-window SLO burn verdict is
  breaching (slo.py);
- ``fold_lag`` — graftfeed's worst live-view fold lag exceeds
  ``MODIN_TPU_INGEST_FOLD_LAG_MS``: ingest is outrunning view
  maintenance and staleness-bounded reads are paying forced folds.

Every evaluation is exception-isolated: a broken rule logs nothing and
trips nothing, it never reaches the sampler loop.
"""

from __future__ import annotations

import json
import os
import pathlib
import time
from collections import deque
from typing import Callable, List, Optional

from modin_tpu.observability.watch.timeseries import note_alloc

#: sliding evaluation window (seconds); module-level for tests/smoke
WINDOW_S = 60.0

#: per-rule re-trip spacing (the evidence bundle has its own shared
#: rate limit; this keeps the watch.trip.* counters readable too)
RULE_COOLDOWN_S = 30.0

LATENCY_SHIFT_FACTOR = 2.0
LATENCY_FLOOR_S = 0.005
LATENCY_MIN_SAMPLES = 8
SPILL_MIN_EVENTS = 4
SHED_MIN_EVENTS = 4

#: ring-excerpt depth captured into evidence bundles
EVIDENCE_RING_SAMPLES = 120


class Tripwire:
    """One declarative rule: ``check(service, now)`` returns a detail
    string when tripped, None otherwise."""

    __slots__ = ("name", "description", "_check", "last_tripped", "trips")

    def __init__(
        self,
        name: str,
        description: str,
        check: Callable[[object, float], Optional[str]],
    ) -> None:
        note_alloc()
        self.name = name
        self.description = description
        self._check = check
        self.last_tripped: Optional[float] = None
        self.trips = 0

    def evaluate(self, service: object, now: float) -> Optional[str]:
        if (
            self.last_tripped is not None
            and now - self.last_tripped < RULE_COOLDOWN_S
        ):
            return None
        try:
            detail = self._check(service, now)
        except Exception:
            return None  # a broken rule must never reach the sampler loop
        if detail is not None:
            self.last_tripped = now
            self.trips += 1
        return detail


# ---------------------------------------------------------------------- #
# the rule catalog
# ---------------------------------------------------------------------- #


def _latency_shift(service, now: float) -> Optional[str]:
    rings = service.rings
    recent = rings.quantile("serving.query_wall_s", 0.99, WINDOW_S, now)
    baseline = rings.quantile(
        "serving.query_wall_s", 0.99, WINDOW_S, now, end_offset_s=WINDOW_S
    )
    if recent is None or baseline is None or baseline <= 0:
        return None
    ring = rings.get("serving.query_wall_s")
    recent_n = ring.window_count(WINDOW_S, now)
    base_delta = ring.hist_delta(now - 2 * WINDOW_S, now - WINDOW_S)
    base_n = base_delta[2] if base_delta is not None else 0
    if recent_n < LATENCY_MIN_SAMPLES or base_n < LATENCY_MIN_SAMPLES:
        return None
    if recent < LATENCY_FLOOR_S:
        return None
    if recent >= LATENCY_SHIFT_FACTOR * baseline:
        return (
            f"query p99 shifted {recent * 1e3:.1f}ms vs trailing baseline "
            f"{baseline * 1e3:.1f}ms ({recent / baseline:.1f}x over "
            f"{WINDOW_S:g}s windows, n={recent_n})"
        )
    return None


def _recompile_storm(service, now: float) -> Optional[str]:
    ring = service.rings.get("compile.storm_signatures")
    if ring is None:
        return None
    window = ring.between(now - WINDOW_S, now)
    if len(window) < 2:
        return None
    growth = float(window[-1][1]) - float(window[0][1])
    if growth >= 1:
        return (
            f"recompile-storm signatures grew by {growth:g} (now "
            f"{window[-1][1]:g}) inside {WINDOW_S:g}s — shape/dtype churn "
            "is defeating the executable cache"
        )
    return None


def _spill_thrash(service, now: float) -> Optional[str]:
    rings = service.rings
    spills = rings.delta("memory.device.spill", WINDOW_S, now)
    if spills is None or spills < SPILL_MIN_EVENTS:
        return None

    def hits(t0: float, t1: float) -> float:
        total = 0.0
        for name in ("fusion.cache.hit", "sortcache.hit", "view.hit"):
            ring = rings.get(name)
            if ring is None:
                continue
            window = ring.between(t0, t1)
            if len(window) >= 2:
                delta = float(window[-1][1]) - float(window[0][1])
                total += max(delta, 0.0)
        return total

    recent_hits = hits(now - WINDOW_S, now)
    prior_hits = hits(now - 2 * WINDOW_S, now - WINDOW_S)
    if recent_hits < prior_hits:
        return (
            f"{spills:g} device spills in {WINDOW_S:g}s while cache hits "
            f"fell ({prior_hits:g} -> {recent_hits:g}): the ledger is "
            "evicting caches the workload is consuming"
        )
    return None


def _shed_spike(service, now: float) -> Optional[str]:
    shed = service.rings.delta("serving.shed", WINDOW_S, now)
    if shed is not None and shed >= SHED_MIN_EVENTS:
        return (
            f"{shed:g} queries shed in {WINDOW_S:g}s — the admission gate "
            "is rejecting sustained load"
        )
    return None


def _slo_burn(service, now: float) -> Optional[str]:
    breaching = service.slo.breaching(now)
    if not breaching:
        return None
    parts = ", ".join(
        f"{tenant} (fast={verdict['fast_burn']}, slow={verdict['slow_burn']}, "
        f"objective={verdict['objective_ms']:g}ms)"
        for tenant, verdict in breaching.items()
    )
    return f"SLO error budget burning faster than sustainable for: {parts}"


def _fold_lag(service, now: float) -> Optional[str]:
    import sys

    ingest_mod = sys.modules.get("modin_tpu.ingest")
    if ingest_mod is None or not ingest_mod.INGEST_ON:
        return None
    from modin_tpu.config import IngestFoldLagMs

    bound_ms = float(IngestFoldLagMs.get())
    lag_ms = ingest_mod.max_fold_lag_ms()
    if lag_ms > bound_ms:
        return (
            f"live-view fold lag {lag_ms:.0f}ms exceeds the "
            f"{bound_ms:g}ms bound (MODIN_TPU_INGEST_FOLD_LAG_MS) — "
            "ingest is outrunning view maintenance; staleness-bounded "
            "reads are paying forced synchronous folds"
        )
    return None


def default_rules() -> List[Tripwire]:
    return [
        Tripwire(
            "latency_shift",
            "query-latency p99 shifted vs the trailing baseline window",
            _latency_shift,
        ),
        Tripwire(
            "recompile_storm",
            "compile-ledger recompile-storm signature count grew",
            _recompile_storm,
        ),
        Tripwire(
            "spill_thrash",
            "device spill burst while cache hit traffic fell",
            _spill_thrash,
        ),
        Tripwire(
            "shed_spike",
            "admission-gate shed burst",
            _shed_spike,
        ),
        Tripwire(
            "slo_burn",
            "a tenant's multi-window SLO burn rate is breaching",
            _slo_burn,
        ),
        Tripwire(
            "fold_lag",
            "graftfeed live-view fold lag exceeds the configured bound",
            _fold_lag,
        ),
    ]


# ---------------------------------------------------------------------- #
# evidence capture
# ---------------------------------------------------------------------- #


def capture_evidence(
    rule: str, detail: str, service
) -> Optional[str]:
    """Write one evidence bundle for a tripped rule; returns the path.

    Rate-limited through the flight recorder's shared claim-token window
    (one incident -> one artifact set, shared with breaker-open dumps);
    returns None when rate-limited or the write failed.  Never raises —
    it runs on the sampler thread.
    """
    from modin_tpu.observability import flight_recorder as _fr

    claimed = _fr.claim_dump_window()
    if claimed is None:
        return None
    try:
        from modin_tpu.config import TraceDir
        from modin_tpu.observability import meters as _meters
        from modin_tpu.observability import spans as _spans
        from modin_tpu.observability.chrome_trace import to_chrome_trace

        bundle = {
            "kind": "graftwatch-evidence",
            "rule": rule,
            "detail": detail,
            "tripped_at_unix_s": time.time(),
            # the chrome-trace segment: whatever the flight ring holds
            # right now (empty while tracing is off — the bundle says so
            # rather than omitting the key)
            "trace": to_chrome_trace(
                _fr.flight_snapshot(),
                other_data={"reason": f"watch.trip.{rule}", "detail": detail},
                counters=_spans.counter_samples(),
            ),
            "metrics": _meters.snapshot(),
            "rings": service.rings.excerpt(EVIDENCE_RING_SAMPLES),
            "slo": service.slo.health(),
        }
        outdir = pathlib.Path(TraceDir.get())
        outdir.mkdir(parents=True, exist_ok=True)
        path = outdir / (
            f"watchtrip_{rule}_{os.getpid()}_{int(time.time() * 1e3)}.json"
        )
        path.write_text(json.dumps(bundle))
        from modin_tpu.logging.metrics import emit_metric

        emit_metric("watch.evidence", 1)
        return str(path)
    except Exception:
        _fr.release_dump_claim(claimed)
        return None


class TripwireEngine:
    """Evaluates the rule catalog each tick and owns the recent-trip ring."""

    def __init__(self, service) -> None:
        note_alloc()
        self._service = service
        self.rules = default_rules()
        self.recent: deque = deque(maxlen=32)

    def on_tick(self, now: float) -> None:
        for rule in self.rules:
            detail = rule.evaluate(self._service, now)
            if detail is None:
                continue
            try:
                from modin_tpu.logging.metrics import emit_metric

                emit_metric(f"watch.trip.{rule.name}", 1)
            except Exception:
                pass
            evidence = capture_evidence(rule.name, detail, self._service)
            self.recent.append(
                {
                    "rule": rule.name,
                    "detail": detail,
                    "at_unix_s": round(time.time(), 3),
                    "evidence": evidence,
                }
            )

    def snapshot(self) -> List[dict]:
        return list(self.recent)
