"""graftwatch time-series rings: bounded history over the telemetry seams.

Everything graftmeter/graftscope expose is *instantaneous* — a counter
total, a gauge value, a cumulative histogram.  Operability questions are
about *time*: "what is p99 over the last 60 seconds", "how many spill
bytes per second right now", "did the storm count grow this minute".
This module holds the answer machinery:

- :class:`Ring` — one bounded deque of ``(t_monotonic, value)`` samples
  for one series, typed like the meter kinds (counter / gauge /
  histogram) with the derived reads each kind supports: counters get
  windowed ``delta``/``rate`` (cumulative-total subtraction, clamped at
  zero so a registry ``reset()`` reads as a restart, not a negative
  rate), histograms get windowed ``quantile`` (cumulative-bucket
  subtraction between the window's edges, interpolated inside the
  bucket), gauges get ``latest``/window min/max.

- :class:`RingStore` — name -> Ring, cardinality-capped by the same
  ``MODIN_TPU_METERS_MAX_SERIES`` guard the meter registry uses, with a
  JSON-safe ``excerpt()`` for evidence bundles and ``/statusz``.

- :class:`Sampler` — the one background thread (daemon, named
  ``modin-tpu-watch-sampler``): every ``MODIN_TPU_WATCH_INTERVAL_S`` it
  folds the meter registry snapshot, the device/host ledger gauges, the
  admission gate's queue depth / in-flight counts, and the
  compile-ledger totals into the store, then hands the tick to the
  tripwire engine.  A sampler crash emits ``watch.sampler.died`` and
  degrades the whole service to disabled — telemetry must never take a
  query down.

Allocation accounting: every Ring / Tripwire / tracker construction calls
:func:`note_alloc`; ``watch.watch_alloc_count()`` exposes the counter so
tests can assert the zero-overhead-when-off contract the graftscope way.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from modin_tpu.concurrency import named_lock

#: ring capacity in samples (per series).  At the default 1s interval this
#: is ~8.5 minutes of history — enough for the slow SLO window with slack.
#: Module-level so tests can shrink it; read at Ring construction.
RING_SAMPLES = 512

_alloc_count = 0


def note_alloc() -> None:
    """Count one graftwatch object construction (the zero-alloc assertion
    counter shared by rings, trackers, and tripwires)."""
    global _alloc_count
    _alloc_count += 1


def alloc_count() -> int:
    return _alloc_count


#: histogram ring sample payload: (bucket upper bounds, cumulative counts
#: per bound, overall count, overall sum) — the meter snapshot's shape,
#: flattened to tuples so samples are immutable
HistSample = Tuple[Tuple[float, ...], Tuple[int, ...], int, float]


class Ring:
    """Bounded time-series of one metric family.

    Writes come from the sampler thread, reads from HTTP handler threads
    and the tripwire engine; the per-ring lock makes the copy-out reads
    safe (``list(deque)`` racing an append raises "deque mutated during
    iteration") at a cost the 1 Hz sampler never notices."""

    __slots__ = ("name", "kind", "_samples", "_lock")

    def __init__(self, name: str, kind: str, maxlen: Optional[int] = None):
        note_alloc()
        self.name = name
        self.kind = kind
        self._samples: deque = deque(maxlen=maxlen or RING_SAMPLES)
        self._lock = named_lock("watch.ring")

    def __len__(self) -> int:
        return len(self._samples)

    def append(self, t: float, value: Any) -> None:
        with self._lock:
            self._samples.append((t, value))

    def samples(self) -> List[tuple]:
        with self._lock:
            return list(self._samples)

    def latest(self) -> Optional[tuple]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def between(self, t0: float, t1: float) -> List[tuple]:
        """Samples with ``t0 <= t <= t1`` (oldest first)."""
        return [s for s in self.samples() if t0 <= s[0] <= t1]

    # -- counter reads --------------------------------------------------- #

    def delta(
        self, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Cumulative-total growth over the trailing window (>= 0).

        None with fewer than two in-window samples.  A negative raw delta
        (the underlying registry was reset mid-window) clamps to the last
        sample's absolute value — the restart's own accumulation."""
        now = time.monotonic() if now is None else now
        window = self.between(now - window_s, now)
        if len(window) < 2:
            return None
        raw = float(window[-1][1]) - float(window[0][1])
        return raw if raw >= 0 else float(window[-1][1])

    def rate(
        self, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        """Per-second growth over the trailing window, or None."""
        now = time.monotonic() if now is None else now
        window = self.between(now - window_s, now)
        if len(window) < 2:
            return None
        dt = window[-1][0] - window[0][0]
        if dt <= 0:
            return None
        delta = float(window[-1][1]) - float(window[0][1])
        if delta < 0:
            delta = float(window[-1][1])
        return delta / dt

    # -- gauge reads ----------------------------------------------------- #

    def window_minmax(
        self, window_s: float, now: Optional[float] = None
    ) -> Optional[Tuple[float, float]]:
        now = time.monotonic() if now is None else now
        window = self.between(now - window_s, now)
        if not window:
            return None
        values = [float(s[1]) for s in window]
        return (min(values), max(values))

    # -- histogram reads ------------------------------------------------- #

    def hist_delta(
        self, t0: float, t1: float
    ) -> Optional[Tuple[Tuple[float, ...], List[int], int]]:
        """``(bounds, per-bucket counts, total)`` of the observations that
        landed between the first sample at/after ``t0`` and the last at/
        before ``t1`` — cumulative-bucket subtraction between the window's
        edge samples.  None when the window holds no usable pair or saw
        no observations."""
        window = self.between(t0, t1)
        if not window:
            return None
        last = window[-1][1]
        first: Optional[HistSample] = None
        if len(window) > 1:
            first = window[0][1]
        bounds, cums, count, _total_sum = last
        if first is not None and first[0] == bounds:
            base_cums, base_count = first[1], first[2]
        else:
            # bucket layout changed (registry reset + re-bucket) or a
            # single-sample window: bill the last sample's full history
            base_cums, base_count = (0,) * len(cums), 0
        counts = [max(c - b, 0) for c, b in zip(cums, base_cums)]
        total = max(count - base_count, 0)
        # de-cumulate: per-bucket counts from the cumulative deltas
        per_bucket: List[int] = []
        prev = 0
        for c in counts:
            per_bucket.append(max(c - prev, 0))
            prev = c
        overflow = max(total - sum(per_bucket), 0)
        per_bucket.append(overflow)
        return (bounds, per_bucket, total)

    def quantile(
        self,
        q: float,
        window_s: float,
        now: Optional[float] = None,
        end_offset_s: float = 0.0,
    ) -> Optional[float]:
        """Estimated q-quantile of the observations inside the trailing
        window (``end_offset_s`` shifts the window back: the tripwires'
        baseline window is ``quantile(q, W, end_offset_s=W)``)."""
        now = time.monotonic() if now is None else now
        t1 = now - end_offset_s
        delta = self.hist_delta(t1 - window_s, t1)
        if delta is None:
            return None
        bounds, per_bucket, total = delta
        if total <= 0:
            return None
        target = q * total
        seen = 0.0
        for i, bucket_count in enumerate(per_bucket):
            if bucket_count == 0:
                continue
            if seen + bucket_count >= target:
                if i >= len(bounds):  # overflow bucket
                    return float(bounds[-1]) if bounds else None
                lo = float(bounds[i - 1]) if i > 0 else 0.0
                hi = float(bounds[i])
                frac = (target - seen) / bucket_count
                return lo + (hi - lo) * frac
            seen += bucket_count
        return float(bounds[-1]) if bounds else None

    def window_count(
        self, window_s: float, now: Optional[float] = None
    ) -> int:
        """Histogram observations inside the trailing window (0 if none)."""
        now = time.monotonic() if now is None else now
        delta = self.hist_delta(now - window_s, now)
        return delta[2] if delta is not None else 0


class RingStore:
    """Thread-safe name -> :class:`Ring` (sampler writes, HTTP/tripwires
    read), cardinality-capped like the meter registry."""

    def __init__(self) -> None:
        note_alloc()
        self._lock = named_lock("watch.rings")
        self._rings: Dict[str, Ring] = {}
        self.dropped_series = 0

    def _max_series(self) -> int:
        try:
            from modin_tpu.config import MetersMaxSeries

            return int(MetersMaxSeries.get())
        except ImportError:
            return 2048

    def observe(self, name: str, kind: str, value: Any, t: float) -> None:
        with self._lock:
            ring = self._rings.get(name)
            if ring is None:
                if len(self._rings) >= self._max_series():
                    self.dropped_series += 1
                    return
                ring = self._rings[name] = Ring(name, kind)
            ring.append(t, value)

    def observe_meter(self, name: str, series: dict, t: float) -> None:
        """Fold one meter-registry snapshot entry into its ring."""
        kind = series.get("kind", "counter")
        if kind == "histogram":
            bounds = tuple(float(b) for b, _c in series.get("buckets", []))
            cums = tuple(int(c) for _b, c in series.get("buckets", []))
            value: Any = (
                bounds, cums, int(series.get("count", 0)),
                float(series.get("sum", 0.0)),
            )
        elif kind == "gauge":
            value = series.get("value", 0.0)
        else:
            value = series.get("total", 0.0)
        self.observe(name, kind, value, t)

    def get(self, name: str) -> Optional[Ring]:
        with self._lock:
            return self._rings.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rings)

    def rate(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        ring = self.get(name)
        return ring.rate(window_s, now) if ring is not None else None

    def delta(
        self, name: str, window_s: float, now: Optional[float] = None
    ) -> Optional[float]:
        ring = self.get(name)
        return ring.delta(window_s, now) if ring is not None else None

    def quantile(
        self,
        name: str,
        q: float,
        window_s: float,
        now: Optional[float] = None,
        end_offset_s: float = 0.0,
    ) -> Optional[float]:
        ring = self.get(name)
        if ring is None:
            return None
        return ring.quantile(q, window_s, now, end_offset_s)

    def excerpt(self, last_n: int = 60) -> dict:
        """JSON-safe tail of every ring (evidence bundles, ``/statusz``)."""
        with self._lock:
            rings = list(self._rings.items())
        out: Dict[str, dict] = {}
        for name, ring in rings:
            tail = ring.samples()[-last_n:]
            out[name] = {
                "kind": ring.kind,
                "samples": [
                    [round(t, 3), _json_safe_value(v)] for t, v in tail
                ],
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self.dropped_series = 0


def _json_safe_value(value: Any) -> Any:
    if isinstance(value, tuple):  # histogram sample
        bounds, cums, count, total_sum = value
        return {
            "buckets": [[b, c] for b, c in zip(bounds, cums)],
            "count": count,
            "sum": total_sum,
        }
    return value


# ---------------------------------------------------------------------- #
# the sampler thread
# ---------------------------------------------------------------------- #


#: ring names the sampler reads LIVE each tick (step 2 below); the meter
#: registry holds same-named gauges updated only at spill passes, and its
#: stale copies must not interleave into the same rings
_DIRECT_SAMPLED = frozenset(
    {"memory.device.resident_bytes", "memory.host.cache_bytes"}
)


class Sampler:
    """The graftwatch background sampling loop (one daemon thread).

    ``on_tick`` runs after every successful sample pass (the tripwire
    engine); ``on_died`` runs once if the loop crashes, AFTER the
    ``watch.sampler.died`` metric is emitted — the service uses it to
    degrade itself to disabled without joining the dying thread.
    """

    THREAD_NAME = "modin-tpu-watch-sampler"

    def __init__(
        self,
        store: RingStore,
        on_tick: Optional[Callable[[float], None]] = None,
        on_died: Optional[Callable[[BaseException], None]] = None,
    ) -> None:
        note_alloc()
        self._store = store
        self._on_tick = on_tick
        self._on_died = on_died
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._obs_span_stack: Any = None
        self._obs_scopes: Any = None
        self.ticks = 0
        self.last_tick_t: Optional[float] = None
        self.died = False
        self.error: Optional[str] = None

    # -- lifecycle ------------------------------------------------------- #

    def start(self) -> None:
        """Start the loop (idempotent: a live thread is left running)."""
        if self._thread is not None and self._thread.is_alive():
            return
        # a FRESH event per run, never clear() of the shared one: a prior
        # run whose stop() join timed out (a tick stalled past the join
        # budget) still holds its own — set — event, so when its stalled
        # tick returns it exits instead of reviving alongside this run
        self._stop = threading.Event()
        self.died = False
        self.error = None
        self.ticks = 0  # per-run: a restart starts its own tick count
        self.last_tick_t = None
        from modin_tpu.observability import meters as graftmeter
        from modin_tpu.observability import spans as graftscope

        # the sampler's emitted samples bill whoever started the service
        self._obs_span_stack = graftscope.snapshot_stack()
        self._obs_scopes = graftmeter.snapshot_scopes()
        self._thread = threading.Thread(
            target=self._run, name=self.THREAD_NAME, daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        """Stop and join the loop (idempotent; never called from the
        sampler thread itself)."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            if thread is not threading.current_thread():
                thread.join(timeout)
        self._thread = None

    def is_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _interval_s(self) -> float:
        from modin_tpu.config import WatchIntervalS

        return max(float(WatchIntervalS.get()), 0.001)

    # -- the loop -------------------------------------------------------- #

    def _run(self) -> None:
        from modin_tpu.observability import meters as graftmeter
        from modin_tpu.observability import spans as graftscope

        graftscope.seed_thread(self._obs_span_stack)
        graftmeter.seed_thread_scopes(self._obs_scopes)
        stop = self._stop  # THIS run's event (see start(): a later start
        # swaps in a fresh one, which must not revive a stalled run)
        try:
            while not stop.is_set():
                self.sample_once()
                if self._on_tick is not None:
                    self._on_tick(time.monotonic())
                if stop.wait(self._interval_s()):
                    break
        except BaseException as err:  # noqa: BLE001 - the degrade contract
            if self._thread is not threading.current_thread():
                # superseded run: stop()/start() already replaced this
                # thread — a crash during its teardown must not degrade
                # the healthy restarted service
                return
            # telemetry must never take queries down: record the crash,
            # emit the counter, and let the service disable itself
            self.died = True
            self.error = f"{type(err).__name__}: {err}"
            try:
                from modin_tpu.logging.metrics import emit_metric

                emit_metric("watch.sampler.died", 1)
            except Exception:
                pass
            if self._on_died is not None:
                try:
                    self._on_died(err)
                except Exception:
                    pass
        finally:
            graftmeter.seed_thread_scopes(None)
            graftscope.seed_thread(None)

    def sample_once(self, now: Optional[float] = None) -> None:
        """One sampling pass over every seam (also callable directly by
        tests and the smoke gate for deterministic ticks)."""
        now = time.monotonic() if now is None else now
        store = self._store

        # 1. the meter registry (the watch service holds a registry
        #    acquire for its lifetime, so series exist even with
        #    MODIN_TPU_METERS=0).  Names the direct seams below sample
        #    live are SKIPPED here: the registry's copy is the value last
        #    emitted at a spill pass — possibly minutes stale — and
        #    interleaving it with the live ledger reading at the same
        #    tick would halve the ring and invent min/max excursions.
        from modin_tpu.observability import meters as _meters

        for name, series in _meters.snapshot().get("series", {}).items():
            if name in _DIRECT_SAMPLED:
                continue
            store.observe_meter(name, series, now)

        # 2. device/host ledger gauges, via the one shared sampling seam
        from modin_tpu.observability import spans as _spans

        device_bytes, host_bytes = _spans._ledger_bytes()
        store.observe(
            "memory.device.resident_bytes", "gauge", device_bytes, now
        )
        store.observe("memory.host.cache_bytes", "gauge", host_bytes, now)

        # 3. admission-gate pressure (only when serving is imported; the
        #    sampler must never trigger an import chain)
        gate_mod = sys.modules.get("modin_tpu.serving.gate")
        if gate_mod is not None:
            try:
                queued, running = gate_mod.counter_sample()
            except Exception:
                queued, running = 0, 0
            store.observe("serving.gate.queued", "gauge", queued, now)
            store.observe("serving.gate.running", "gauge", running, now)

        # 4. compile-ledger deltas (totals are O(1); the storm count walks
        #    the signature table once per tick)
        from modin_tpu.observability.compile_ledger import get_compile_ledger

        ledger = get_compile_ledger()
        compiles, compile_s = ledger.totals()
        store.observe("compile.total", "counter", compiles, now)
        store.observe("compile.wall_s", "counter", compile_s, now)
        store.observe(
            "compile.storm_signatures",
            "gauge",
            len(ledger.recompile_storms()),
            now,
        )

        self.ticks += 1
        self.last_tick_t = now
