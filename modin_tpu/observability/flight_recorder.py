"""Flight recorder: dump the span ring buffer when the system degrades.

While tracing is on, every finished span also lands in a bounded ring
buffer (``spans._RING``, sized by ``MODIN_TPU_TRACE_FLIGHT_RECORDER_SIZE``).
When the resilience layer decides something is seriously wrong — a circuit
breaker trips OPEN, or a device failure is classified terminal (OOM,
device-lost, retries exhausted) — it calls ``dump_flight_record`` and the
last N spans are written as a chrome://tracing-loadable JSON file under
``MODIN_TPU_TRACE_DIR``: the trace that *led up to* the failure, tying the
PR-1 failure taxonomy to its preceding query activity.  The dump also
embeds the graftmeter metrics snapshot taken at dump time under
``otherData.metrics`` (counter state used to die with the process) plus
the counter-track samples (device/host residency, live spans).

The dump is strictly best-effort: it never raises into the query path, it
does nothing while tracing is off (so the default-off mode keeps its
near-zero overhead), and consecutive dumps are rate-limited so a flapping
breaker cannot fill a disk.
"""

from __future__ import annotations

import json
import os
import pathlib
import re
import threading
import time
from typing import List, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.observability import spans as _spans
from modin_tpu.observability.chrome_trace import to_chrome_trace

#: minimum seconds between dumps (module-level so tests can lower it)
MIN_DUMP_INTERVAL_S = 5.0

#: "no dump yet" sentinel.  NOT 0.0: time.monotonic() is machine uptime on
#: Linux, so `now - 0.0 < interval` spuriously rate-limits every dump for
#: the first `interval` seconds after boot (observed: a test pinning a
#: 3600s interval failed for the first hour of container uptime).
_NEVER_DUMPED = float("-inf")
_last_dump = _NEVER_DUMPED
_dump_lock = named_lock("flight.dump")

_REASON_SANITIZE = re.compile(r"[^A-Za-z0-9_.-]+")


def flight_snapshot() -> List[object]:
    """The spans currently in the ring (oldest first); empty when off."""
    ring = _spans._RING
    return list(ring) if ring is not None else []


def claim_dump_window() -> Optional[float]:
    """Claim the shared dump rate-limit window; None when rate-limited.

    One claim token guards EVERY on-disk failure artifact — breaker/terminal
    flight dumps here and graftwatch tripwire evidence bundles — so one
    incident produces one artifact set, however many detectors saw it.
    A successful claim must be followed by either a completed write or
    :func:`release_dump_claim` (a failed write must not consume the window).
    """
    global _last_dump
    with _dump_lock:
        now = time.monotonic()
        if now - _last_dump < MIN_DUMP_INTERVAL_S:
            return None
        _last_dump = now
        return now


def release_dump_claim(claimed: float) -> None:
    """Release OUR claim after a failed write (see the failure path in
    :func:`dump_flight_record` for why only the matching claim resets)."""
    global _last_dump
    with _dump_lock:
        if _last_dump == claimed:
            _last_dump = _NEVER_DUMPED


def reset_for_tests() -> None:
    """Clear the ring, counter samples, and the rate limiter (test isolation)."""
    global _last_dump
    ring = _spans._RING
    if ring is not None:
        ring.clear()
    counters = _spans._COUNTERS
    if counters is not None:
        counters.clear()
    _last_dump = _NEVER_DUMPED


def dump_flight_record(reason: str, detail: str = "") -> Optional[str]:
    """Write the ring to a trace file; returns the path or None.

    None means "nothing dumped" — tracing off, empty ring, rate-limited,
    or the write failed.  Never raises: the caller is the failure path
    itself and must stay failure-free.
    """
    if not _spans.TRACE_ON:
        return None
    ring = _spans._RING
    if not ring:
        return None
    claimed = claim_dump_window()  # concurrent callers back off
    if claimed is None:
        return None
    with _dump_lock:
        snapshot = list(ring)
        counters = list(_spans._COUNTERS or ())
    try:
        # counter state at dump time: breaker-open / terminal-failure
        # forensics keep the aggregated metrics the process dies with
        # (empty series while MODIN_TPU_METERS is off — still recorded, so
        # the dump says "meters were off" rather than omitting the key)
        from modin_tpu.observability import meters as _meters

        metrics_snapshot = _meters.snapshot()
    except Exception:
        metrics_snapshot = None
    try:
        from modin_tpu.config import TraceDir

        outdir = pathlib.Path(TraceDir.get())
        outdir.mkdir(parents=True, exist_ok=True)
        safe_reason = _REASON_SANITIZE.sub("_", reason) or "fault"
        path = outdir / (
            f"flightrec_{safe_reason}_{os.getpid()}_{int(time.time() * 1e3)}"
            ".trace.json"
        )
        trace = to_chrome_trace(
            snapshot,
            other_data={
                "reason": reason,
                "detail": detail,
                "spans": len(snapshot),
                "metrics": metrics_snapshot,
            },
            counters=counters,
        )
        # atomic: a dump that dies mid-write (ENOSPC, crash) must leave NO
        # truncated trace file — forensics tooling loads whatever it finds
        from modin_tpu.utils.atomic_io import atomic_write_json

        atomic_write_json(str(path), trace)
        return str(path)
    except Exception:
        # best-effort by contract: a failed dump must not worsen the fault —
        # and must not consume the rate-limit window (a transiently
        # unwritable TraceDir would otherwise suppress the next, possibly
        # successful, dump of the real fault; partial-WRITE failures release
        # it too, not just open/serialize ones).  Only release OUR claim:
        # under simultaneous breaker-opens (graftgate: many threads, one
        # incident) another thread may have claimed a newer window and be
        # writing its dump right now — unconditionally zeroing the limiter
        # here would re-open the window behind its back and let a third
        # caller double-dump the same incident.
        release_dump_claim(claimed)
        return None
