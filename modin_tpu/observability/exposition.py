"""graftmeter exposition: render a meter snapshot for the outside world.

Two formats over the same :func:`modin_tpu.observability.meters.snapshot`
dict:

- :func:`to_prometheus` — the Prometheus text exposition format (one
  ``# HELP``/``# TYPE`` block per series; histograms expand to
  ``_bucket{le=...}`` / ``_sum`` / ``_count`` lines), ready to serve from
  any scrape endpoint a host application owns.  Metric names are the
  emitted dotted names with non-alphanumerics folded to ``_`` and a
  ``modin_tpu_`` prefix.
- :func:`to_json` — the snapshot as a canonical JSON document (stable key
  order) for log shipping / test assertions.

:func:`parse_prometheus` is the minimal validating parser the smoke gate
(scripts/metrics_smoke.py) uses to prove the text format is well-formed —
every non-comment line must be ``name{labels} value`` with a float value,
every TYPE must be a known meter kind, and histogram bucket counts must be
cumulative and monotonic.

:func:`meter_rollup` compresses a snapshot into the small headline dict
bench.py attaches to every streamed section line (dispatches, compiles,
bytes parsed, cache hits, spills).
"""

from __future__ import annotations

import fnmatch
import json
import re
from typing import Any, Dict, List, Optional

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")
_SAMPLE_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?"
    r" (?P<value>[0-9eE+.\-]+|NaN|\+Inf|-Inf)$"
)

PROMETHEUS_KINDS = {"counter", "gauge", "histogram"}


def prometheus_name(metric_name: str) -> str:
    """``resilience.engine.deploy.oom`` -> ``modin_tpu_resilience_engine_deploy_oom``."""
    return "modin_tpu_" + _NAME_SANITIZE.sub("_", metric_name)


def _fmt(value: Any) -> str:
    if value is None:
        return "NaN"
    if isinstance(value, int) or (isinstance(value, float) and value.is_integer()):
        return str(int(value))
    return repr(float(value))


#: name -> rendered HELP text; bounded because exposition accepts
#: arbitrary snapshots (the live registry itself is cardinality-capped)
_HELP_CACHE: Dict[str, str] = {}
_HELP_CACHE_MAX = 4096


def help_text(metric_name: str) -> str:
    """The ``# HELP`` line body for a metric: the family's description
    from the ``METRICS`` registry (the 3-tuples already carry one),
    whitespace-normalized and escaped per the Prometheus text format
    (``\\`` -> ``\\\\``, newline -> ``\\n``).  Ad-hoc names not matching
    any registry pattern keep the generic fallback text."""
    cached = _HELP_CACHE.get(metric_name)
    if cached is not None:
        return cached
    text = f"modin_tpu metric {metric_name}"
    try:
        from modin_tpu.logging.metrics import METRICS

        for entry in METRICS:
            if fnmatch.fnmatchcase(metric_name, entry[0]) and len(entry) > 2:
                text = " ".join(str(entry[2]).split())
                break
    except ImportError:  # teardown: keep the fallback
        pass
    text = text.replace("\\", "\\\\").replace("\n", "\\n")
    if len(_HELP_CACHE) < _HELP_CACHE_MAX:
        _HELP_CACHE[metric_name] = text
    return text


def to_prometheus(snapshot: dict) -> str:
    """Render a meter snapshot as Prometheus text exposition format."""
    lines: List[str] = []
    for name, series in snapshot.get("series", {}).items():
        kind = series.get("kind", "counter")
        promname = prometheus_name(name)
        lines.append(f"# HELP {promname} {help_text(name)}")
        if kind == "histogram":
            lines.append(f"# TYPE {promname} histogram")
            for bound, cum_count in series.get("buckets", []):
                lines.append(
                    f'{promname}_bucket{{le="{_fmt(float(bound))}"}} {cum_count}'
                )
            lines.append(f'{promname}_bucket{{le="+Inf"}} {series["count"]}')
            lines.append(f"{promname}_sum {_fmt(series['sum'])}")
            lines.append(f"{promname}_count {series['count']}")
        elif kind == "gauge":
            lines.append(f"# TYPE {promname} gauge")
            lines.append(f"{promname} {_fmt(series.get('value'))}")
        else:
            lines.append(f"# TYPE {promname} counter")
            lines.append(f"{promname} {_fmt(series.get('total', 0))}")
    lines.append("")
    return "\n".join(lines)


def to_json(snapshot: dict, indent: Optional[int] = None) -> str:
    """Render a meter snapshot as canonical JSON."""
    return json.dumps(snapshot, sort_keys=True, indent=indent)


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Validate + parse Prometheus text format back into
    ``{name: {"type": kind, "samples": {sample_line_name+labels: value}}}``.

    Raises ``ValueError`` on any malformed line, unknown TYPE, or a
    non-monotonic histogram bucket sequence — the smoke gate's proof that
    the exposition is loadable by a real scraper.
    """
    out: Dict[str, dict] = {}
    current_type: Dict[str, str] = {}
    last_bucket: Dict[str, float] = {}
    help_texts: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.rstrip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            if len(parts) < 3 or not parts[2]:
                raise ValueError(f"malformed HELP line: {line!r}")
            help_texts[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4:
                raise ValueError(f"malformed TYPE line: {line!r}")
            _, _, name, kind = parts
            if kind not in PROMETHEUS_KINDS:
                raise ValueError(f"unknown TYPE {kind!r} for {name}: {line!r}")
            current_type[name] = kind
            out[name] = {
                "type": kind,
                "samples": {},
                "help": help_texts.get(name),
            }
            continue
        if line.startswith("#"):
            raise ValueError(f"unknown comment directive: {line!r}")
        m = _SAMPLE_LINE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line: {line!r}")
        sample_name = m.group("name")
        base = sample_name
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in current_type:
                base = base[: -len(suffix)]
                break
        if base not in current_type:
            raise ValueError(f"sample before TYPE declaration: {line!r}")
        value = float(m.group("value").replace("+Inf", "inf").replace("-Inf", "-inf"))
        if sample_name.endswith("_bucket"):
            prev = last_bucket.get(base, float("-inf"))
            if value < prev:
                raise ValueError(
                    f"non-cumulative histogram buckets for {base}: "
                    f"{value} after {prev}"
                )
            last_bucket[base] = value
        out[base]["samples"][sample_name + (m.group("labels") or "")] = value
    return out


def meter_rollup(snapshot: Optional[dict] = None) -> dict:
    """Headline counters from a snapshot (bench.py's per-section line).

    ``{dispatches, compiles, compile_s, bytes_parsed, io_reads, spills,
    cache_hits: {fused, sorted_rep, plan_scan}, api_calls}`` — everything
    defaults to 0 so section lines are schema-stable whether or not the
    section touched a given subsystem.

    ``bytes_parsed`` sums ``io.read.bytes``, which bills the SOURCE file
    size per physical read (best-effort, FileDispatcher): it measures how
    much data the query went to disk for, and does not shrink when
    projection pushdown parses a column subset of the same file — that
    benefit shows up in ``plan.scan.pruned_columns``, not here.
    """
    if snapshot is None:
        from modin_tpu.observability import meters

        snapshot = meters.snapshot()
    series = snapshot.get("series", {})

    def total(name: str) -> Any:
        return series.get(name, {}).get("total", 0)

    def hist(name: str, field: str) -> Any:
        return series.get(name, {}).get(field, 0) or 0

    api_calls = sum(
        s.get("count", 0)
        for name, s in series.items()
        if name.startswith("pandas-api.")
    )
    return {
        "dispatches": total("engine.dispatch"),
        "compiles": total("engine.compile"),
        "compile_s": round(float(total("engine.compile_s")), 4),
        "bytes_parsed": int(hist("io.read.bytes", "sum")),
        "io_reads": hist("io.read.bytes", "count"),
        "spills": total("memory.device.spill"),
        "cache_hits": {
            "fused": total("fusion.cache.hit"),
            "sorted_rep": total("sortcache.hit"),
            "plan_scan": total("plan.scan.cache_hit"),
        },
        "api_calls": api_calls,
        # graftcost: estimated work + padding waste (0 when cost capture
        # was off or the section dispatched nothing)
        "cost": {
            "est_flops": float(total("engine.cost.flops")),
            "est_bytes": float(total("engine.cost.bytes")),
            "padded_bytes": int(total("engine.cost.padded_bytes")),
            "padding_waste_bytes": int(
                total("engine.cost.padding_waste_bytes")
            ),
        },
    }
