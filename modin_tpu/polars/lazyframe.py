"""Polars-flavored LazyFrame: recorded verb chain, executed on collect().

Reference design: modin/polars/lazyframe.py:17 (trivially-eager LazyFrame).
The TPU build records the plan and replays it on ``collect()``; because the
underlying device dispatch is already asynchronous, consecutive device verbs
pipeline without host synchronization between them.
"""

from __future__ import annotations

from typing import Any, Callable, List


class LazyFrame:
    """A recorded chain of DataFrame verbs."""

    def __init__(self, data: Any = None, *, _source: Any = None, _plan: Any = None):
        from modin_tpu.polars.dataframe import DataFrame

        if _source is not None:
            self._source = _source
        else:
            self._source = DataFrame(data)
        self._plan: List[Callable] = list(_plan or [])

    @classmethod
    def _from_eager(cls, df: Any) -> "LazyFrame":
        return cls(_source=df)

    def _chain(self, step: Callable) -> "LazyFrame":
        return LazyFrame(_source=self._source, _plan=self._plan + [step])

    def collect(self) -> Any:
        result = self._source
        for step in self._plan:
            result = step(result)
        return result

    def fetch(self, n_rows: int = 500) -> Any:
        return self._chain(lambda df: df.head(n_rows)).collect()

    @property
    def columns(self) -> list:
        # resolving the schema requires replaying column-changing steps
        return self.collect().columns

    def lazy(self) -> "LazyFrame":
        return self


def _make_lazy_verb(name: str):
    def verb(self: LazyFrame, *args: Any, **kwargs: Any) -> LazyFrame:
        return self._chain(lambda df: getattr(df, name)(*args, **kwargs))

    verb.__name__ = name
    return verb


for _name in [
    "select", "drop", "rename", "with_columns", "filter", "sort", "head",
    "tail", "limit", "slice", "unique", "join", "vstack", "drop_nulls",
    "fill_null",
]:
    setattr(LazyFrame, _name, _make_lazy_verb(_name))


def _lazy_group_by(self: LazyFrame, *by: Any) -> "LazyGroupBy":
    return LazyGroupBy(self, by)


LazyFrame.group_by = _lazy_group_by


class LazyGroupBy:
    def __init__(self, lf: LazyFrame, by: tuple):
        self._lf = lf
        self._by = by

    def agg(self, *exprs: Any) -> LazyFrame:
        by = self._by
        return self._lf._chain(lambda df: df.group_by(*by).agg(*exprs))

    def __getattr__(self, name: str):
        if name in ("sum", "mean", "min", "max", "count", "len"):
            by = self._by

            def verb() -> LazyFrame:
                return self._lf._chain(lambda df: getattr(df.group_by(*by), name)())

            return verb
        raise AttributeError(name)
