"""Polars-flavored eager DataFrame over the same query compilers.

Reference design: modin/polars/dataframe.py:38 — a polars API surface whose
storage is the framework's query compiler, so the device fast paths (sharded
columns, segment groupby, distributed sort) back polars verbs too.

Implemented verbs: select, drop, rename, with_columns, filter, sort, head,
tail, limit, slice, unique, group_by (agg/sum/mean/min/max/count/len),
join, vstack, hstack, get_column(s), to_pandas, describe, item, equals,
plus expression objects (``col``/``lit``) with arithmetic/comparison/agg
chains.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Union

import numpy as np
import pandas


class Expr:
    """A minimal polars-like expression: a deferred column computation."""

    def __init__(self, fn, name: str, agg: Optional[str] = None):
        self._fn = fn  # (modin DataFrame) -> modin Series
        self._name = name
        self._agg = agg

    def _evaluate(self, df):
        return self._fn(df)

    def alias(self, name: str) -> "Expr":
        return Expr(self._fn, name, self._agg)

    def _binary(self, other: Any, op) -> "Expr":
        if isinstance(other, Expr):
            return Expr(
                lambda df: op(self._fn(df), other._fn(df)), self._name, self._agg
            )
        return Expr(lambda df: op(self._fn(df), other), self._name, self._agg)

    def __add__(self, other):
        return self._binary(other, lambda a, b: a + b)

    def __sub__(self, other):
        return self._binary(other, lambda a, b: a - b)

    def __mul__(self, other):
        return self._binary(other, lambda a, b: a * b)

    def __truediv__(self, other):
        return self._binary(other, lambda a, b: a / b)

    def __radd__(self, other):
        return self._binary(other, lambda a, b: b + a)

    def __rmul__(self, other):
        return self._binary(other, lambda a, b: b * a)

    def __rsub__(self, other):
        return self._binary(other, lambda a, b: b - a)

    def __rtruediv__(self, other):
        return self._binary(other, lambda a, b: b / a)

    def __neg__(self):
        return Expr(lambda df: -self._fn(df), self._name, self._agg)

    def __lt__(self, other):
        return self._binary(other, lambda a, b: a < b)

    def __le__(self, other):
        return self._binary(other, lambda a, b: a <= b)

    def __gt__(self, other):
        return self._binary(other, lambda a, b: a > b)

    def __ge__(self, other):
        return self._binary(other, lambda a, b: a >= b)

    def __eq__(self, other):  # type: ignore[override]
        return self._binary(other, lambda a, b: a == b)

    def __ne__(self, other):  # type: ignore[override]
        return self._binary(other, lambda a, b: a != b)

    def __and__(self, other):
        return self._binary(other, lambda a, b: a & b)

    def __or__(self, other):
        return self._binary(other, lambda a, b: a | b)

    def _aggregate(self, agg: str) -> "Expr":
        return Expr(self._fn, self._name, agg=agg)

    def sum(self) -> "Expr":
        return self._aggregate("sum")

    def mean(self) -> "Expr":
        return self._aggregate("mean")

    def min(self) -> "Expr":
        return self._aggregate("min")

    def max(self) -> "Expr":
        return self._aggregate("max")

    def count(self) -> "Expr":
        return self._aggregate("count")

    def std(self) -> "Expr":
        return self._aggregate("std")

    def var(self) -> "Expr":
        return self._aggregate("var")


def col(name: str) -> Expr:
    """Reference a column (polars.col)."""
    return Expr(lambda df: df[name], name)


def lit(value: Any) -> Expr:
    """A literal value (polars.lit)."""
    return Expr(lambda df: value, "literal")


class DataFrame:
    """Polars-flavored eager frame over a modin_tpu query compiler."""

    def __init__(self, data: Any = None, *, _query_compiler: Any = None):
        from modin_tpu.pandas.dataframe import DataFrame as PandasLayerFrame

        if _query_compiler is not None:
            self._query_compiler = _query_compiler
        elif isinstance(data, DataFrame):
            self._query_compiler = data._query_compiler.copy()
        elif isinstance(data, PandasLayerFrame):
            self._query_compiler = data._query_compiler.copy()
        else:
            self._query_compiler = PandasLayerFrame(data)._query_compiler

    # -- plumbing ------------------------------------------------------- #

    @property
    def _md(self):
        """The pandas-layer view of the same compiler (shared, no copy)."""
        from modin_tpu.pandas.dataframe import DataFrame as PandasLayerFrame

        return PandasLayerFrame(query_compiler=self._query_compiler)

    @classmethod
    def _from_md(cls, md) -> "DataFrame":
        return cls(_query_compiler=md._query_compiler)

    # -- introspection -------------------------------------------------- #

    @property
    def columns(self) -> List[str]:
        return list(self._query_compiler.columns)

    @property
    def width(self) -> int:
        return self._query_compiler.get_axis_len(1)

    @property
    def height(self) -> int:
        return self._query_compiler.get_axis_len(0)

    @property
    def shape(self) -> tuple:
        return (self.height, self.width)

    @property
    def dtypes(self) -> list:
        return list(self._query_compiler.dtypes)

    @property
    def schema(self) -> dict:
        return dict(zip(self.columns, self.dtypes))

    def __len__(self) -> int:
        return self.height

    def __repr__(self) -> str:
        return f"shape: {self.shape}\n" + repr(self._md.reset_index(drop=True))

    def __getitem__(self, key: Any):
        if isinstance(key, str):
            return Series(_md=self._md[key])
        if isinstance(key, list):
            return self.select(key)
        if isinstance(key, slice):
            return self._from_md(self._md.iloc[key])
        raise TypeError(f"unsupported key type {type(key)}")

    # -- conversions ---------------------------------------------------- #

    def to_pandas(self) -> pandas.DataFrame:
        return self._md._to_pandas().reset_index(drop=True)

    def to_numpy(self) -> np.ndarray:
        return self._md.to_numpy()

    def item(self, row: Optional[int] = None, column: Any = None):
        if row is None and column is None:
            if self.shape != (1, 1):
                raise ValueError("can only call .item() on a 1x1 frame")
            return self.to_pandas().iloc[0, 0]
        return self.to_pandas().iloc[row, self.columns.index(column) if isinstance(column, str) else column]

    def equals(self, other: "DataFrame") -> bool:
        return self.to_pandas().equals(other.to_pandas())

    # -- verbs ---------------------------------------------------------- #

    def _resolve_exprs(self, exprs: Any) -> List[Expr]:
        if isinstance(exprs, (Expr, str)):
            exprs = [exprs]
        out = []
        for e in exprs:
            out.append(col(e) if isinstance(e, str) else e)
        return out

    def select(self, *exprs: Any) -> "DataFrame":
        flat: List[Any] = []
        for e in exprs:
            flat.extend(e) if isinstance(e, (list, tuple)) else flat.append(e)
        resolved = self._resolve_exprs(flat)
        md = self._md
        pieces = {}
        for e in resolved:
            result = e._evaluate(md)
            if e._agg is not None:
                result = getattr(result, e._agg)()
            pieces[e._name] = result
        import modin_tpu.pandas as mpd

        # polars broadcasts length-1/scalar results to the frame length when
        # any full-length column is selected
        full = [v for v in pieces.values() if hasattr(v, "_query_compiler")]
        if full:
            first_name = next(
                k for k, v in pieces.items() if hasattr(v, "_query_compiler")
            )
            out = pieces[first_name].to_frame(first_name)
            for name, v in pieces.items():
                if name == first_name:
                    continue
                out[name] = v  # scalars broadcast in setitem
            out = out[list(pieces)]  # restore requested order
        else:
            out = mpd.DataFrame({k: [v] for k, v in pieces.items()})
        return self._from_md(out)

    def drop(self, *columns: Any) -> "DataFrame":
        cols = []
        for c in columns:
            cols.extend(c) if isinstance(c, (list, tuple)) else cols.append(c)
        return self._from_md(self._md.drop(columns=cols))

    def rename(self, mapping: dict) -> "DataFrame":
        return self._from_md(self._md.rename(columns=mapping))

    def with_columns(self, *exprs: Any, **named: Any) -> "DataFrame":
        flat: List[Any] = []
        for e in exprs:
            flat.extend(e) if isinstance(e, (list, tuple)) else flat.append(e)
        base = self._md  # polars evaluates every expr against the INPUT frame
        md = base.copy()
        for e in self._resolve_exprs(flat):
            md[e._name] = e._evaluate(base)
        for name, e in named.items():
            value = e._evaluate(base) if isinstance(e, Expr) else e
            md[name] = value
        return self._from_md(md)

    def filter(self, *predicates: Any) -> "DataFrame":
        md = self._md
        mask = None
        for p in predicates:
            m = p._evaluate(md) if isinstance(p, Expr) else p
            mask = m if mask is None else (mask & m)
        return self._from_md(md[mask])

    def sort(self, by: Any, *more_by: Any, descending: Any = False) -> "DataFrame":
        cols = [by, *more_by] if not isinstance(by, list) else [*by, *more_by]
        cols = [c._name if isinstance(c, Expr) else c for c in cols]
        if isinstance(descending, bool):
            ascending: Any = not descending
        else:
            ascending = [not d for d in descending]
        return self._from_md(
            self._md.sort_values(cols, ascending=ascending, kind="stable").reset_index(
                drop=True
            )
        )

    def head(self, n: int = 5) -> "DataFrame":
        return self._from_md(self._md.head(n))

    def tail(self, n: int = 5) -> "DataFrame":
        return self._from_md(self._md.tail(n))

    def limit(self, n: int = 5) -> "DataFrame":
        return self.head(n)

    def slice(self, offset: int, length: Optional[int] = None) -> "DataFrame":
        stop = None if length is None else offset + length
        return self._from_md(self._md.iloc[offset:stop])

    def unique(self, subset: Any = None, keep: str = "first") -> "DataFrame":
        if keep in ("first", "any"):
            keep_arg: Any = "first"
        elif keep == "none":
            keep_arg = False  # polars: drop every row that has a duplicate
        else:
            keep_arg = keep
        return self._from_md(
            self._md.drop_duplicates(subset=subset, keep=keep_arg, ignore_index=True)
        )

    def group_by(self, *by: Any) -> "GroupBy":
        keys = []
        for b in by:
            keys.extend(b) if isinstance(b, (list, tuple)) else keys.append(b)
        keys = [k._name if isinstance(k, Expr) else k for k in keys]
        return GroupBy(self, keys)

    def join(self, other: "DataFrame", on: Any = None, how: str = "inner", left_on: Any = None, right_on: Any = None, suffix: str = "_right") -> "DataFrame":
        if how in ("semi", "anti"):
            keys = on if on is not None else left_on
            key_list = [keys] if isinstance(keys, str) else list(keys)
            right_keys = (
                other._md[key_list]
                if right_on is None
                else other._md[[right_on] if isinstance(right_on, str) else list(right_on)]
            ).drop_duplicates()
            merged = self._md.merge(
                right_keys.rename(
                    columns=dict(
                        zip(
                            right_keys.columns,
                            key_list,
                        )
                    )
                ),
                on=key_list,
                how="left",
                indicator=True,
            )
            keep = "both" if how == "semi" else "left_only"
            md = merged[merged["_merge"] == keep].drop(columns=["_merge"])
            return self._from_md(md.reset_index(drop=True))
        how_map = {"inner": "inner", "left": "left", "outer": "outer", "full": "outer", "cross": "cross"}
        md = self._md.merge(
            other._md,
            on=on,
            left_on=left_on,
            right_on=right_on,
            how=how_map.get(how, how),
            suffixes=("", suffix),
        )
        return self._from_md(md.reset_index(drop=True))

    def vstack(self, other: "DataFrame") -> "DataFrame":
        import modin_tpu.pandas as mpd

        return self._from_md(mpd.concat([self._md, other._md], ignore_index=True))

    def hstack(self, other: "DataFrame") -> "DataFrame":
        import modin_tpu.pandas as mpd

        return self._from_md(mpd.concat([self._md, other._md], axis=1))

    def describe(self) -> "DataFrame":
        return self._from_md(self._md.describe().reset_index())

    def lazy(self) -> "LazyFrame":
        from modin_tpu.polars.lazyframe import LazyFrame

        return LazyFrame._from_eager(self)

    def get_column(self, name: str) -> "Series":
        return self[name]

    def get_columns(self) -> List["Series"]:
        return [self[c] for c in self.columns]

    def drop_nulls(self, subset: Any = None) -> "DataFrame":
        return self._from_md(self._md.dropna(subset=subset).reset_index(drop=True))

    def fill_null(self, value: Any) -> "DataFrame":
        return self._from_md(self._md.fillna(value))

    def mean(self) -> "DataFrame":
        return self._from_md(self._md.mean().to_frame().T)

    def sum(self) -> "DataFrame":
        return self._from_md(self._md.sum().to_frame().T)

    def max(self) -> "DataFrame":
        return self._from_md(self._md.max().to_frame().T)

    def min(self) -> "DataFrame":
        return self._from_md(self._md.min().to_frame().T)

    def median(self) -> "DataFrame":
        return self._from_md(self._md.median().to_frame().T)

    def std(self, ddof: int = 1) -> "DataFrame":
        return self._from_md(self._md.std(ddof=ddof).to_frame().T)

    def var(self, ddof: int = 1) -> "DataFrame":
        return self._from_md(self._md.var(ddof=ddof).to_frame().T)

    def product(self) -> "DataFrame":
        return self._from_md(self._md.prod().to_frame().T)

    def quantile(self, quantile: float, interpolation: str = "nearest") -> "DataFrame":
        return self._from_md(
            self._md.quantile(quantile, interpolation=interpolation).to_frame().T
        )

    def n_unique(self) -> "DataFrame":
        return self._from_md(self._md.nunique().to_frame().T)

    def null_count(self) -> "DataFrame":
        return self._from_md(self._md.isna().sum().to_frame().T)

    def corr(self, **kwargs: Any) -> "DataFrame":
        return self._from_md(self._md.corr(**kwargs).reset_index(drop=True))

    # -- horizontal aggregations ---------------------------------------- #

    def sum_horizontal(self) -> "Series":
        return Series(_md=self._md.sum(axis=1).rename("sum"))

    def mean_horizontal(self) -> "Series":
        return Series(_md=self._md.mean(axis=1).rename("mean"))

    def min_horizontal(self) -> "Series":
        return Series(_md=self._md.min(axis=1).rename("min"))

    def max_horizontal(self) -> "Series":
        return Series(_md=self._md.max(axis=1).rename("max"))

    # -- reshaping ------------------------------------------------------- #

    def unpivot(
        self,
        on: Any = None,
        *,
        index: Any = None,
        variable_name: str = "variable",
        value_name: str = "value",
    ) -> "DataFrame":
        md = self._md.melt(
            id_vars=index, value_vars=on,
            var_name=variable_name, value_name=value_name,
        )
        return self._from_md(md)

    melt = unpivot

    def pivot(
        self, on: Any, *, index: Any = None, values: Any = None,
        aggregate_function: str = "first",
    ) -> "DataFrame":
        on_list = [on] if isinstance(on, str) else list(on)
        values_list = (
            None if values is None
            else [values] if isinstance(values, str) else list(values)
        )
        index_list = (
            None if index is None
            else [index] if isinstance(index, str) else list(index)
        )
        # polars defaults: the unnamed role takes all remaining columns
        if index_list is None and values_list is None:
            raise ValueError("pivot requires at least one of `index`/`values`")
        if values_list is None:
            values_list = [
                c for c in self.columns if c not in on_list and c not in index_list
            ]
        if index_list is None:
            index_list = [
                c for c in self.columns if c not in on_list and c not in values_list
            ]
        md = self._md.pivot_table(
            index=index_list,
            columns=on_list[0] if len(on_list) == 1 else on_list,
            values=values_list[0] if len(values_list) == 1 else values_list,
            aggfunc=aggregate_function, sort=False,
        )
        return self._from_md(md.reset_index())

    def transpose(self, include_header: bool = False) -> "DataFrame":
        pdf = self.to_pandas().T.reset_index(drop=not include_header)
        if include_header:
            pdf = pdf.rename(columns={"index": "column"})
        offset = 1 if include_header else 0  # data columns start at column_0
        pdf.columns = [
            c if isinstance(c, str) else f"column_{i - offset}"
            for i, c in enumerate(pdf.columns)
        ]
        return DataFrame(pdf)

    def reverse(self) -> "DataFrame":
        return self._from_md(self._md.iloc[::-1].reset_index(drop=True))

    def partition_by(self, by: Any, *more_by: str, as_dict: bool = False):
        keys = ([by] if isinstance(by, str) else list(by)) + list(more_by)
        pdf = self.to_pandas()
        groups = list(pdf.groupby(keys, sort=False))
        frames = [DataFrame(g.reset_index(drop=True)) for _, g in groups]
        if as_dict:
            return {k: f for (k, _), f in zip(groups, frames)}
        return frames

    # -- rows / export ---------------------------------------------------- #

    def row(self, index: int, *, named: bool = False):
        values = self.to_pandas().iloc[index]
        if named:
            return dict(values)
        return tuple(values)

    def rows(self, *, named: bool = False) -> list:
        pdf = self.to_pandas()
        if named:
            return [dict(zip(pdf.columns, r)) for r in pdf.itertuples(index=False)]
        return [tuple(r) for r in pdf.itertuples(index=False)]

    def iter_rows(self, *, named: bool = False):
        return iter(self.rows(named=named))

    def iter_columns(self):
        for c in self.columns:
            yield self[c]

    def to_dict(self, *, as_series: bool = True) -> dict:
        if as_series:
            return {c: self[c] for c in self.columns}
        pdf = self.to_pandas()
        return {c: pdf[c].tolist() for c in pdf.columns}

    def to_dicts(self) -> list:
        return self.rows(named=True)

    def to_series(self, index: int = 0) -> "Series":
        return self[self.columns[index]]

    def to_struct(self, name: str = "") -> "Series":
        return Series(_md=pandas_series_from(self.rows(named=True), name))

    # -- column surgery --------------------------------------------------- #

    def get_column_index(self, name: str) -> int:
        return list(self.columns).index(name)

    def insert_column(self, index: int, column: "Series") -> "DataFrame":
        md = self._md.copy()
        md.insert(index, column.name, column._md_series)
        return self._from_md(md)

    def replace_column(self, index: int, column: "Series") -> "DataFrame":
        md = self._md.copy()
        label = md.columns[index]
        md[label] = column._md_series
        return self._from_md(md.rename(columns={label: column.name}))

    def drop_in_place(self, name: str) -> "Series":
        series = self[name]
        self._query_compiler = self._md.drop(columns=[name])._query_compiler
        return series

    def clear(self, n: int = 0) -> "DataFrame":
        # schema only — no device->host transfer of the data
        schema = dict(zip(self.columns, self._query_compiler.dtypes))
        if n == 0:
            return DataFrame(
                pandas.DataFrame({c: pandas.array([], dtype=d) for c, d in schema.items()})
            )
        # n null rows, keeping the original schema (polars semantics; int
        # columns use pandas' nullable Int64 to hold nulls)
        data = {}
        for c, dt in schema.items():
            if dt.kind in "iu":
                data[c] = pandas.array([None] * n, dtype="Int64")
            elif dt.kind == "f":
                data[c] = pandas.array([np.nan] * n, dtype=dt)
            elif dt.kind == "b":
                data[c] = pandas.array([None] * n, dtype="boolean")
            else:
                data[c] = pandas.array([None] * n, dtype=dt)
        return DataFrame(pandas.DataFrame(data))

    def estimated_size(self, unit: str = "b") -> float:
        nbytes = float(self.to_pandas().memory_usage(index=False, deep=True).sum())
        scale = {"b": 1, "kb": 1024, "mb": 1024**2, "gb": 1024**3, "tb": 1024**4}
        return nbytes / scale[unit]

    def pipe(self, function, *args: Any, **kwargs: Any):
        return function(self, *args, **kwargs)

    def fold(self, operation):
        acc = self.to_series(0)
        for i in range(1, len(self.columns)):
            acc = operation(acc, self.to_series(i))
        return acc


def pandas_series_from(values: list, name: str):
    import modin_tpu.pandas as mpd

    return mpd.Series(values, name=name or None)


class GroupBy:
    """Deferred polars group_by."""

    def __init__(self, df: DataFrame, keys: List[str]):
        self._df = df
        self._keys = keys

    def agg(self, *exprs: Any) -> DataFrame:
        flat: List[Any] = []
        for e in exprs:
            flat.extend(e) if isinstance(e, (list, tuple)) else flat.append(e)
        base = self._df._md
        md = base.copy()
        specs = []  # (source_column, agg, output_name)
        for i, e in enumerate(flat):
            if isinstance(e, str):
                e = col(e).sum()
            tmp = f"__agg_src_{i}__"
            # evaluate the expression against the ORIGINAL frame so computed
            # expressions ((col(a)*2).sum()) and aliases work
            md[tmp] = e._evaluate(base)
            specs.append((tmp, e._agg or "first", e._name))
        gb = md.groupby(self._keys, sort=True)
        pieces = [
            getattr(gb[tmp], agg)().rename(out) for tmp, agg, out in specs
        ]
        import modin_tpu.pandas as mpd

        out = mpd.concat(pieces, axis=1) if len(pieces) > 1 else pieces[0].to_frame()
        return DataFrame._from_md(out.reset_index())

    def _simple(self, agg: str) -> DataFrame:
        md = self._df._md
        result = getattr(md.groupby(self._keys, sort=True), agg)(numeric_only=False)
        return DataFrame._from_md(result.reset_index())

    def sum(self) -> DataFrame:
        return self._simple("sum")

    def mean(self) -> DataFrame:
        return self._simple("mean")

    def min(self) -> DataFrame:
        return self._simple("min")

    def max(self) -> DataFrame:
        return self._simple("max")

    def count(self) -> DataFrame:
        return self._simple("count")

    def len(self) -> DataFrame:
        md = self._df._md
        result = md.groupby(self._keys, sort=True).size()
        out = result.to_frame("len")
        return DataFrame._from_md(out.reset_index())


class Series:
    """Polars-flavored series over a modin_tpu Series."""

    def __init__(self, name: Any = None, values: Any = None, *, _md: Any = None):
        import modin_tpu.pandas as mpd

        if _md is not None:
            self._md_series = _md
        elif values is not None:
            self._md_series = mpd.Series(values, name=name)
        else:
            self._md_series = mpd.Series(name if not isinstance(name, str) else [], name=name if isinstance(name, str) else None)

    @property
    def name(self) -> Optional[str]:
        return self._md_series.name

    @property
    def dtype(self):
        return self._md_series.dtype

    def __len__(self) -> int:
        return len(self._md_series)

    def __repr__(self) -> str:
        return f"shape: ({len(self)},)\n" + repr(self._md_series)

    def to_pandas(self) -> pandas.Series:
        return self._md_series._to_pandas().reset_index(drop=True)

    def to_numpy(self) -> np.ndarray:
        return self._md_series.to_numpy()

    def to_list(self) -> list:
        return self._md_series.to_list()

    def sum(self):
        return self._md_series.sum()

    def mean(self):
        return self._md_series.mean()

    def min(self):
        return self._md_series.min()

    def max(self):
        return self._md_series.max()

    def unique(self) -> "Series":
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series(self._md_series.unique(), name=self.name))

    def _wrap_op(self, other: Any, op) -> "Series":
        if isinstance(other, Series):
            other = other._md_series
        return Series(_md=op(self._md_series, other))

    def __add__(self, other):
        return self._wrap_op(other, lambda a, b: a + b)

    def __sub__(self, other):
        return self._wrap_op(other, lambda a, b: a - b)

    def __mul__(self, other):
        return self._wrap_op(other, lambda a, b: a * b)

    def __truediv__(self, other):
        return self._wrap_op(other, lambda a, b: a / b)

    def __gt__(self, other):
        return self._wrap_op(other, lambda a, b: a > b)

    def __lt__(self, other):
        return self._wrap_op(other, lambda a, b: a < b)
