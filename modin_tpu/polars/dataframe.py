"""Polars-flavored eager DataFrame over the same query compilers.

Reference design: modin/polars/dataframe.py:38 — a polars API surface whose
storage is the framework's query compiler, so the device fast paths (sharded
columns, segment groupby, distributed sort) back polars verbs too.

Implemented verbs: select, drop, rename, with_columns, filter, sort, head,
tail, limit, slice, unique, group_by (agg/sum/mean/min/max/count/len),
join, vstack, hstack, get_column(s), to_pandas, describe, item, equals,
plus expression objects (``col``/``lit``) with arithmetic/comparison/agg
chains.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Union

import numpy as np
import pandas


class Expr:
    """A minimal polars-like expression: a deferred column computation."""

    def __init__(self, fn, name: str, agg: Optional[str] = None):
        self._fn = fn  # (modin DataFrame) -> modin Series
        self._name = name
        self._agg = agg

    def _evaluate(self, df):
        return self._fn(df)

    def alias(self, name: str) -> "Expr":
        return Expr(self._fn, name, self._agg)

    def _binary(self, other: Any, op) -> "Expr":
        if isinstance(other, Expr):
            return Expr(
                lambda df: op(self._fn(df), other._fn(df)), self._name, self._agg
            )
        return Expr(lambda df: op(self._fn(df), other), self._name, self._agg)

    def __add__(self, other):
        return self._binary(other, lambda a, b: a + b)

    def __sub__(self, other):
        return self._binary(other, lambda a, b: a - b)

    def __mul__(self, other):
        return self._binary(other, lambda a, b: a * b)

    def __truediv__(self, other):
        return self._binary(other, lambda a, b: a / b)

    def __radd__(self, other):
        return self._binary(other, lambda a, b: b + a)

    def __rmul__(self, other):
        return self._binary(other, lambda a, b: b * a)

    def __rsub__(self, other):
        return self._binary(other, lambda a, b: b - a)

    def __rtruediv__(self, other):
        return self._binary(other, lambda a, b: b / a)

    def __neg__(self):
        return Expr(lambda df: -self._fn(df), self._name, self._agg)

    def __lt__(self, other):
        return self._binary(other, lambda a, b: a < b)

    def __le__(self, other):
        return self._binary(other, lambda a, b: a <= b)

    def __gt__(self, other):
        return self._binary(other, lambda a, b: a > b)

    def __ge__(self, other):
        return self._binary(other, lambda a, b: a >= b)

    def __eq__(self, other):  # type: ignore[override]
        return self._binary(other, lambda a, b: a == b)

    def __ne__(self, other):  # type: ignore[override]
        return self._binary(other, lambda a, b: a != b)

    def __and__(self, other):
        return self._binary(other, lambda a, b: a & b)

    def __or__(self, other):
        return self._binary(other, lambda a, b: a | b)

    def _aggregate(self, agg: str) -> "Expr":
        return Expr(self._fn, self._name, agg=agg)

    def sum(self) -> "Expr":
        return self._aggregate("sum")

    def mean(self) -> "Expr":
        return self._aggregate("mean")

    def min(self) -> "Expr":
        return self._aggregate("min")

    def max(self) -> "Expr":
        return self._aggregate("max")

    def count(self) -> "Expr":
        return self._aggregate("count")

    def std(self) -> "Expr":
        return self._aggregate("std")

    def var(self) -> "Expr":
        return self._aggregate("var")


def col(name: str) -> Expr:
    """Reference a column (polars.col)."""
    return Expr(lambda df: df[name], name)


def lit(value: Any) -> Expr:
    """A literal value (polars.lit)."""
    return Expr(lambda df: value, "literal")


class DataFrame:
    """Polars-flavored eager frame over a modin_tpu query compiler."""

    def __init__(self, data: Any = None, *, _query_compiler: Any = None):
        from modin_tpu.pandas.dataframe import DataFrame as PandasLayerFrame

        if _query_compiler is not None:
            self._query_compiler = _query_compiler
        elif isinstance(data, DataFrame):
            self._query_compiler = data._query_compiler.copy()
        elif isinstance(data, PandasLayerFrame):
            self._query_compiler = data._query_compiler.copy()
        else:
            self._query_compiler = PandasLayerFrame(data)._query_compiler

    # -- plumbing ------------------------------------------------------- #

    @property
    def _md(self):
        """The pandas-layer view of the same compiler (shared, no copy)."""
        from modin_tpu.pandas.dataframe import DataFrame as PandasLayerFrame

        return PandasLayerFrame(query_compiler=self._query_compiler)

    @classmethod
    def _from_md(cls, md) -> "DataFrame":
        return cls(_query_compiler=md._query_compiler)

    # -- introspection -------------------------------------------------- #

    @property
    def columns(self) -> List[str]:
        return list(self._query_compiler.columns)

    @property
    def width(self) -> int:
        return self._query_compiler.get_axis_len(1)

    @property
    def height(self) -> int:
        return self._query_compiler.get_axis_len(0)

    @property
    def shape(self) -> tuple:
        return (self.height, self.width)

    @property
    def dtypes(self) -> list:
        return list(self._query_compiler.dtypes)

    @property
    def schema(self) -> dict:
        return dict(zip(self.columns, self.dtypes))

    def __len__(self) -> int:
        return self.height

    def __repr__(self) -> str:
        return f"shape: {self.shape}\n" + repr(self._md.reset_index(drop=True))

    def __getitem__(self, key: Any):
        if isinstance(key, str):
            return Series(_md=self._md[key])
        if isinstance(key, list):
            return self.select(key)
        if isinstance(key, slice):
            return self._from_md(self._md.iloc[key])
        raise TypeError(f"unsupported key type {type(key)}")

    # -- conversions ---------------------------------------------------- #

    def to_pandas(self) -> pandas.DataFrame:
        return self._md._to_pandas().reset_index(drop=True)

    def to_numpy(self) -> np.ndarray:
        return self._md.to_numpy()

    def item(self, row: Optional[int] = None, column: Any = None):
        if row is None and column is None:
            if self.shape != (1, 1):
                raise ValueError("can only call .item() on a 1x1 frame")
            return self.to_pandas().iloc[0, 0]
        return self.to_pandas().iloc[row, self.columns.index(column) if isinstance(column, str) else column]

    def equals(self, other: "DataFrame") -> bool:
        return self.to_pandas().equals(other.to_pandas())

    # -- verbs ---------------------------------------------------------- #

    def _resolve_exprs(self, exprs: Any) -> List[Expr]:
        if isinstance(exprs, (Expr, str)):
            exprs = [exprs]
        out = []
        for e in exprs:
            out.append(col(e) if isinstance(e, str) else e)
        return out

    def select(self, *exprs: Any) -> "DataFrame":
        flat: List[Any] = []
        for e in exprs:
            flat.extend(e) if isinstance(e, (list, tuple)) else flat.append(e)
        resolved = self._resolve_exprs(flat)
        md = self._md
        pieces = {}
        for e in resolved:
            result = e._evaluate(md)
            if e._agg is not None:
                result = getattr(result, e._agg)()
            pieces[e._name] = result
        import modin_tpu.pandas as mpd

        # polars broadcasts length-1/scalar results to the frame length when
        # any full-length column is selected
        full = [v for v in pieces.values() if hasattr(v, "_query_compiler")]
        if full:
            first_name = next(
                k for k, v in pieces.items() if hasattr(v, "_query_compiler")
            )
            out = pieces[first_name].to_frame(first_name)
            for name, v in pieces.items():
                if name == first_name:
                    continue
                out[name] = v  # scalars broadcast in setitem
            out = out[list(pieces)]  # restore requested order
        else:
            out = mpd.DataFrame({k: [v] for k, v in pieces.items()})
        return self._from_md(out)

    def drop(self, *columns: Any) -> "DataFrame":
        cols = []
        for c in columns:
            cols.extend(c) if isinstance(c, (list, tuple)) else cols.append(c)
        return self._from_md(self._md.drop(columns=cols))

    def rename(self, mapping: dict) -> "DataFrame":
        return self._from_md(self._md.rename(columns=mapping))

    def with_columns(self, *exprs: Any, **named: Any) -> "DataFrame":
        flat: List[Any] = []
        for e in exprs:
            flat.extend(e) if isinstance(e, (list, tuple)) else flat.append(e)
        base = self._md  # polars evaluates every expr against the INPUT frame
        md = base.copy()
        for e in self._resolve_exprs(flat):
            md[e._name] = e._evaluate(base)
        for name, e in named.items():
            value = e._evaluate(base) if isinstance(e, Expr) else e
            md[name] = value
        return self._from_md(md)

    def filter(self, *predicates: Any) -> "DataFrame":
        md = self._md
        mask = None
        for p in predicates:
            m = p._evaluate(md) if isinstance(p, Expr) else p
            mask = m if mask is None else (mask & m)
        return self._from_md(md[mask])

    def sort(self, by: Any, *more_by: Any, descending: Any = False) -> "DataFrame":
        cols = [by, *more_by] if not isinstance(by, list) else [*by, *more_by]
        cols = [c._name if isinstance(c, Expr) else c for c in cols]
        if isinstance(descending, bool):
            ascending: Any = not descending
        else:
            ascending = [not d for d in descending]
        return self._from_md(
            self._md.sort_values(cols, ascending=ascending, kind="stable").reset_index(
                drop=True
            )
        )

    def head(self, n: int = 5) -> "DataFrame":
        return self._from_md(self._md.head(n))

    def tail(self, n: int = 5) -> "DataFrame":
        return self._from_md(self._md.tail(n))

    def limit(self, n: int = 5) -> "DataFrame":
        return self.head(n)

    def slice(self, offset: int, length: Optional[int] = None) -> "DataFrame":
        stop = None if length is None else offset + length
        return self._from_md(self._md.iloc[offset:stop])

    def unique(self, subset: Any = None, keep: str = "first") -> "DataFrame":
        if keep in ("first", "any"):
            keep_arg: Any = "first"
        elif keep == "none":
            keep_arg = False  # polars: drop every row that has a duplicate
        else:
            keep_arg = keep
        return self._from_md(
            self._md.drop_duplicates(subset=subset, keep=keep_arg, ignore_index=True)
        )

    def group_by(self, *by: Any) -> "GroupBy":
        keys = []
        for b in by:
            keys.extend(b) if isinstance(b, (list, tuple)) else keys.append(b)
        keys = [k._name if isinstance(k, Expr) else k for k in keys]
        return GroupBy(self, keys)

    def join(self, other: "DataFrame", on: Any = None, how: str = "inner", left_on: Any = None, right_on: Any = None, suffix: str = "_right") -> "DataFrame":
        if how in ("semi", "anti"):
            keys = on if on is not None else left_on
            key_list = [keys] if isinstance(keys, str) else list(keys)
            right_keys = (
                other._md[key_list]
                if right_on is None
                else other._md[[right_on] if isinstance(right_on, str) else list(right_on)]
            ).drop_duplicates()
            merged = self._md.merge(
                right_keys.rename(
                    columns=dict(
                        zip(
                            right_keys.columns,
                            key_list,
                        )
                    )
                ),
                on=key_list,
                how="left",
                indicator=True,
            )
            keep = "both" if how == "semi" else "left_only"
            md = merged[merged["_merge"] == keep].drop(columns=["_merge"])
            return self._from_md(md.reset_index(drop=True))
        how_map = {"inner": "inner", "left": "left", "outer": "outer", "full": "outer", "cross": "cross"}
        md = self._md.merge(
            other._md,
            on=on,
            left_on=left_on,
            right_on=right_on,
            how=how_map.get(how, how),
            suffixes=("", suffix),
        )
        return self._from_md(md.reset_index(drop=True))

    def vstack(self, other: "DataFrame") -> "DataFrame":
        import modin_tpu.pandas as mpd

        return self._from_md(mpd.concat([self._md, other._md], ignore_index=True))

    def hstack(self, other: "DataFrame") -> "DataFrame":
        import modin_tpu.pandas as mpd

        return self._from_md(mpd.concat([self._md, other._md], axis=1))

    def describe(self) -> "DataFrame":
        return self._from_md(self._md.describe().reset_index())

    def lazy(self) -> "LazyFrame":
        from modin_tpu.polars.lazyframe import LazyFrame

        return LazyFrame._from_eager(self)

    def get_column(self, name: str) -> "Series":
        return self[name]

    def get_columns(self) -> List["Series"]:
        return [self[c] for c in self.columns]

    def drop_nulls(self, subset: Any = None) -> "DataFrame":
        return self._from_md(self._md.dropna(subset=subset).reset_index(drop=True))

    def fill_null(self, value: Any) -> "DataFrame":
        return self._from_md(self._md.fillna(value))

    def mean(self) -> "DataFrame":
        return self._from_md(self._md.mean().to_frame().T)

    def sum(self) -> "DataFrame":
        return self._from_md(self._md.sum().to_frame().T)

    def max(self) -> "DataFrame":
        return self._from_md(self._md.max().to_frame().T)

    def min(self) -> "DataFrame":
        return self._from_md(self._md.min().to_frame().T)

    def median(self) -> "DataFrame":
        return self._from_md(self._md.median().to_frame().T)

    def std(self, ddof: int = 1) -> "DataFrame":
        return self._from_md(self._md.std(ddof=ddof).to_frame().T)

    def var(self, ddof: int = 1) -> "DataFrame":
        return self._from_md(self._md.var(ddof=ddof).to_frame().T)

    def product(self) -> "DataFrame":
        return self._from_md(self._md.prod().to_frame().T)

    def quantile(self, quantile: float, interpolation: str = "nearest") -> "DataFrame":
        return self._from_md(
            self._md.quantile(quantile, interpolation=interpolation).to_frame().T
        )

    def n_unique(self) -> "DataFrame":
        return self._from_md(self._md.nunique().to_frame().T)

    def null_count(self) -> "DataFrame":
        return self._from_md(self._md.isna().sum().to_frame().T)

    def corr(self, **kwargs: Any) -> "DataFrame":
        return self._from_md(self._md.corr(**kwargs).reset_index(drop=True))

    # -- horizontal aggregations ---------------------------------------- #

    def sum_horizontal(self) -> "Series":
        return Series(_md=self._md.sum(axis=1).rename("sum"))

    def mean_horizontal(self) -> "Series":
        return Series(_md=self._md.mean(axis=1).rename("mean"))

    def min_horizontal(self) -> "Series":
        return Series(_md=self._md.min(axis=1).rename("min"))

    def max_horizontal(self) -> "Series":
        return Series(_md=self._md.max(axis=1).rename("max"))

    # -- reshaping ------------------------------------------------------- #

    def unpivot(
        self,
        on: Any = None,
        *,
        index: Any = None,
        variable_name: str = "variable",
        value_name: str = "value",
    ) -> "DataFrame":
        md = self._md.melt(
            id_vars=index, value_vars=on,
            var_name=variable_name, value_name=value_name,
        )
        return self._from_md(md)

    melt = unpivot

    def pivot(
        self, on: Any, *, index: Any = None, values: Any = None,
        aggregate_function: str = "first",
    ) -> "DataFrame":
        on_list = [on] if isinstance(on, str) else list(on)
        values_list = (
            None if values is None
            else [values] if isinstance(values, str) else list(values)
        )
        index_list = (
            None if index is None
            else [index] if isinstance(index, str) else list(index)
        )
        # polars defaults: the unnamed role takes all remaining columns
        if index_list is None and values_list is None:
            raise ValueError("pivot requires at least one of `index`/`values`")
        if values_list is None:
            values_list = [
                c for c in self.columns if c not in on_list and c not in index_list
            ]
        if index_list is None:
            index_list = [
                c for c in self.columns if c not in on_list and c not in values_list
            ]
        md = self._md.pivot_table(
            index=index_list,
            columns=on_list[0] if len(on_list) == 1 else on_list,
            values=values_list[0] if len(values_list) == 1 else values_list,
            aggfunc=aggregate_function, sort=False,
        )
        return self._from_md(md.reset_index())

    def transpose(self, include_header: bool = False) -> "DataFrame":
        pdf = self.to_pandas().T.reset_index(drop=not include_header)
        if include_header:
            pdf = pdf.rename(columns={"index": "column"})
        offset = 1 if include_header else 0  # data columns start at column_0
        pdf.columns = [
            c if isinstance(c, str) else f"column_{i - offset}"
            for i, c in enumerate(pdf.columns)
        ]
        return DataFrame(pdf)

    def reverse(self) -> "DataFrame":
        return self._from_md(self._md.iloc[::-1].reset_index(drop=True))

    def partition_by(self, by: Any, *more_by: str, as_dict: bool = False):
        keys = ([by] if isinstance(by, str) else list(by)) + list(more_by)
        pdf = self.to_pandas()
        groups = list(pdf.groupby(keys, sort=False))
        frames = [DataFrame(g.reset_index(drop=True)) for _, g in groups]
        if as_dict:
            return {k: f for (k, _), f in zip(groups, frames)}
        return frames

    # -- rows / export ---------------------------------------------------- #

    def row(self, index: int, *, named: bool = False):
        values = self.to_pandas().iloc[index]
        if named:
            return dict(values)
        return tuple(values)

    def rows(self, *, named: bool = False) -> list:
        pdf = self.to_pandas()
        if named:
            return [dict(zip(pdf.columns, r)) for r in pdf.itertuples(index=False)]
        return [tuple(r) for r in pdf.itertuples(index=False)]

    def iter_rows(self, *, named: bool = False):
        return iter(self.rows(named=named))

    def iter_columns(self):
        for c in self.columns:
            yield self[c]

    def to_dict(self, *, as_series: bool = True) -> dict:
        if as_series:
            return {c: self[c] for c in self.columns}
        pdf = self.to_pandas()
        return {c: pdf[c].tolist() for c in pdf.columns}

    def to_dicts(self) -> list:
        return self.rows(named=True)

    def to_series(self, index: int = 0) -> "Series":
        return self[self.columns[index]]

    def to_struct(self, name: str = "") -> "Series":
        return Series(_md=pandas_series_from(self.rows(named=True), name))

    # -- column surgery --------------------------------------------------- #

    def get_column_index(self, name: str) -> int:
        return list(self.columns).index(name)

    def insert_column(self, index: int, column: "Series") -> "DataFrame":
        md = self._md.copy()
        md.insert(index, column.name, column._md_series)
        return self._from_md(md)

    def replace_column(self, index: int, column: "Series") -> "DataFrame":
        md = self._md.copy()
        label = md.columns[index]
        md[label] = column._md_series
        return self._from_md(md.rename(columns={label: column.name}))

    def drop_in_place(self, name: str) -> "Series":
        series = self[name]
        self._query_compiler = self._md.drop(columns=[name])._query_compiler
        return series

    def clear(self, n: int = 0) -> "DataFrame":
        # schema only — no device->host transfer of the data
        schema = dict(zip(self.columns, self._query_compiler.dtypes))
        if n == 0:
            return DataFrame(
                pandas.DataFrame({c: pandas.array([], dtype=d) for c, d in schema.items()})
            )
        # n null rows, keeping the original schema (polars semantics; int
        # columns use pandas' nullable Int64 to hold nulls)
        data = {}
        for c, dt in schema.items():
            if dt.kind in "iu":
                data[c] = pandas.array([None] * n, dtype="Int64")
            elif dt.kind == "f":
                data[c] = pandas.array([np.nan] * n, dtype=dt)
            elif dt.kind == "b":
                data[c] = pandas.array([None] * n, dtype="boolean")
            else:
                data[c] = pandas.array([None] * n, dtype=dt)
        return DataFrame(pandas.DataFrame(data))

    def estimated_size(self, unit: str = "b") -> float:
        nbytes = float(self.to_pandas().memory_usage(index=False, deep=True).sum())
        scale = {"b": 1, "kb": 1024, "mb": 1024**2, "gb": 1024**3, "tb": 1024**4}
        return nbytes / scale[unit]

    def pipe(self, function, *args: Any, **kwargs: Any):
        return function(self, *args, **kwargs)

    def fold(self, operation):
        acc = self.to_series(0)
        for i in range(1, len(self.columns)):
            acc = operation(acc, self.to_series(i))
        return acc


def pandas_series_from(values: list, name: str):
    import modin_tpu.pandas as mpd

    return mpd.Series(values, name=name or None)


class GroupBy:
    """Deferred polars group_by."""

    def __init__(self, df: DataFrame, keys: List[str]):
        self._df = df
        self._keys = keys

    def agg(self, *exprs: Any) -> DataFrame:
        flat: List[Any] = []
        for e in exprs:
            flat.extend(e) if isinstance(e, (list, tuple)) else flat.append(e)
        base = self._df._md
        md = base.copy()
        specs = []  # (source_column, agg, output_name)
        for i, e in enumerate(flat):
            if isinstance(e, str):
                e = col(e).sum()
            tmp = f"__agg_src_{i}__"
            # evaluate the expression against the ORIGINAL frame so computed
            # expressions ((col(a)*2).sum()) and aliases work
            md[tmp] = e._evaluate(base)
            specs.append((tmp, e._agg or "first", e._name))
        gb = md.groupby(self._keys, sort=True)
        pieces = [
            getattr(gb[tmp], agg)().rename(out) for tmp, agg, out in specs
        ]
        import modin_tpu.pandas as mpd

        out = mpd.concat(pieces, axis=1) if len(pieces) > 1 else pieces[0].to_frame()
        return DataFrame._from_md(out.reset_index())

    def _simple(self, agg: str) -> DataFrame:
        md = self._df._md
        result = getattr(md.groupby(self._keys, sort=True), agg)(numeric_only=False)
        return DataFrame._from_md(result.reset_index())

    def sum(self) -> DataFrame:
        return self._simple("sum")

    def mean(self) -> DataFrame:
        return self._simple("mean")

    def min(self) -> DataFrame:
        return self._simple("min")

    def max(self) -> DataFrame:
        return self._simple("max")

    def count(self) -> DataFrame:
        return self._simple("count")

    def len(self) -> DataFrame:
        md = self._df._md
        result = md.groupby(self._keys, sort=True).size()
        out = result.to_frame("len")
        return DataFrame._from_md(out.reset_index())


class Series:
    """Polars-flavored series over a modin_tpu Series."""

    def __init__(self, name: Any = None, values: Any = None, *, _md: Any = None):
        import modin_tpu.pandas as mpd

        if _md is not None:
            self._md_series = _md
        elif values is not None:
            self._md_series = mpd.Series(values, name=name)
        else:
            self._md_series = mpd.Series(name if not isinstance(name, str) else [], name=name if isinstance(name, str) else None)

    @property
    def name(self) -> Optional[str]:
        return self._md_series.name

    @property
    def dtype(self):
        return self._md_series.dtype

    def __len__(self) -> int:
        return len(self._md_series)

    def __repr__(self) -> str:
        return f"shape: ({len(self)},)\n" + repr(self._md_series)

    def to_pandas(self) -> pandas.Series:
        return self._md_series._to_pandas().reset_index(drop=True)

    def to_numpy(self) -> np.ndarray:
        return self._md_series.to_numpy()

    def to_list(self) -> list:
        return self._md_series.to_list()

    def sum(self):
        return self._md_series.sum()

    def mean(self):
        return self._md_series.mean()

    def min(self):
        return self._md_series.min()

    def max(self):
        return self._md_series.max()

    def unique(self) -> "Series":
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series(self._md_series.unique(), name=self.name))

    def _wrap_op(self, other: Any, op) -> "Series":
        if isinstance(other, Series):
            other = other._md_series
        return Series(_md=op(self._md_series, other))

    def __add__(self, other):
        return self._wrap_op(other, lambda a, b: a + b)

    def __sub__(self, other):
        return self._wrap_op(other, lambda a, b: a - b)

    def __mul__(self, other):
        return self._wrap_op(other, lambda a, b: a * b)

    def __truediv__(self, other):
        return self._wrap_op(other, lambda a, b: a / b)

    def __gt__(self, other):
        return self._wrap_op(other, lambda a, b: a > b)

    def __lt__(self, other):
        return self._wrap_op(other, lambda a, b: a < b)


# ---------------------------------------------------------------------- #
# Series surface expansion (ref modin/polars/series.py: 167 methods; the
# mixin below + the inline class cover the non-namespace surface, each verb
# delegating to the device-backed modin series)
# ---------------------------------------------------------------------- #


class _SeriesMethods:
    """Bulk polars Series verbs, attached to ``Series`` below."""

    # -- casts / exports ------------------------------------------------ #

    def to_frame(self, name: Optional[str] = None) -> "DataFrame":
        md = self._md_series.rename(name) if name else self._md_series
        return DataFrame._from_md(md.to_frame())

    def to_init_repr(self, n: int = 1000) -> str:
        vals = self.to_list()[:n]
        return f"pl.Series({self.name!r}, {vals!r})"

    def alias(self, name: str) -> "Series":
        return Series(_md=self._md_series.rename(name))

    def rename(self, name: str) -> "Series":
        return self.alias(name)

    def clear(self, n: int = 0) -> "Series":
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series([None] * n, name=self.name, dtype=self.dtype))

    def clone(self) -> "Series":
        return Series(_md=self._md_series.copy())

    def rechunk(self, in_place: bool = False) -> "Series":
        return self

    def set_sorted(self, *, descending: bool = False) -> "Series":
        return self

    def to_physical(self) -> "Series":
        md = self._md_series
        if str(md.dtype) == "category":
            return Series(_md=md.cat.codes)
        return self

    def shrink_dtype(self) -> "Series":
        import modin_tpu.pandas as mpd

        s = self._md_series._to_pandas()
        kind = s.dtype.kind
        if kind in "iu":
            s = pandas.to_numeric(s, downcast="integer")
        elif kind == "f":
            s = pandas.to_numeric(s, downcast="float")
        return Series(_md=mpd.Series(s))

    @property
    def shape(self) -> tuple:
        return (len(self),)

    def len(self) -> int:
        return len(self)

    def item(self, index: Optional[int] = None) -> Any:
        if index is not None:
            return self.to_list()[index]
        if len(self) != 1:
            raise ValueError("can only call .item() if the series is of length 1")
        return self.to_list()[0]

    def chunk_lengths(self) -> list:
        return [len(self)]

    def get_chunks(self) -> list:
        return [self]

    def estimated_size(self, unit: str = "b") -> float:
        nbytes = float(self._md_series.memory_usage(index=False))
        scale = {"b": 1, "kb": 1024, "mb": 1024**2, "gb": 1024**3, "tb": 1024**4}
        return nbytes / scale[unit]

    # -- elementwise math ---------------------------------------------- #

    def _unary_np(self, fn) -> "Series":
        import modin_tpu.pandas as mpd

        s = self._md_series._to_pandas()
        return Series(_md=mpd.Series(pandas.Series(fn(s.to_numpy()), index=s.index, name=s.name)))

    def abs(self) -> "Series":
        return Series(_md=self._md_series.abs())

    def round(self, decimals: int = 0) -> "Series":
        return Series(_md=self._md_series.round(decimals))

    def round_sig_figs(self, digits: int) -> "Series":
        def fn(a):
            with np.errstate(divide="ignore", invalid="ignore"):
                mags = 10.0 ** (digits - 1 - np.floor(np.log10(np.abs(a))))
            out = np.round(a * mags) / mags
            return np.where(a == 0, 0.0, out)

        return self._unary_np(fn)

    def ceil(self) -> "Series":
        return self._unary_np(np.ceil)

    def floor(self) -> "Series":
        return self._unary_np(np.floor)

    def sqrt(self) -> "Series":
        return self._unary_np(np.sqrt)

    def cbrt(self) -> "Series":
        return self._unary_np(np.cbrt)

    def exp(self) -> "Series":
        return self._unary_np(np.exp)

    def log(self, base: float = np.e) -> "Series":
        return self._unary_np(lambda a: np.log(a) / np.log(base))

    def log10(self) -> "Series":
        return self._unary_np(np.log10)

    def log1p(self) -> "Series":
        return self._unary_np(np.log1p)

    def sign(self) -> "Series":
        return self._unary_np(np.sign)

    def sin(self) -> "Series":
        return self._unary_np(np.sin)

    def cos(self) -> "Series":
        return self._unary_np(np.cos)

    def tan(self) -> "Series":
        return self._unary_np(np.tan)

    def cot(self) -> "Series":
        return self._unary_np(lambda a: 1.0 / np.tan(a))

    def sinh(self) -> "Series":
        return self._unary_np(np.sinh)

    def cosh(self) -> "Series":
        return self._unary_np(np.cosh)

    def tanh(self) -> "Series":
        return self._unary_np(np.tanh)

    def arcsin(self) -> "Series":
        return self._unary_np(np.arcsin)

    def arccos(self) -> "Series":
        return self._unary_np(np.arccos)

    def arctan(self) -> "Series":
        return self._unary_np(np.arctan)

    def arcsinh(self) -> "Series":
        return self._unary_np(np.arcsinh)

    def arccosh(self) -> "Series":
        return self._unary_np(np.arccosh)

    def arctanh(self) -> "Series":
        return self._unary_np(np.arctanh)

    def not_(self) -> "Series":
        return Series(_md=~self._md_series)

    def pow(self, exponent: Any) -> "Series":
        return self._wrap_op(exponent, lambda a, b: a**b)

    def dot(self, other: Any) -> float:
        other_md = other._md_series if isinstance(other, Series) else other
        return float((self._md_series * other_md).sum())

    def clip(self, lower_bound: Any = None, upper_bound: Any = None) -> "Series":
        return Series(_md=self._md_series.clip(lower_bound, upper_bound))

    # -- null / nan predicates ------------------------------------------ #

    def is_null(self) -> "Series":
        return Series(_md=self._md_series.isna())

    def is_not_null(self) -> "Series":
        return Series(_md=self._md_series.notna())

    def is_nan(self) -> "Series":
        return self._unary_np(lambda a: np.isnan(a.astype(np.float64)))

    def is_not_nan(self) -> "Series":
        return self._unary_np(lambda a: ~np.isnan(a.astype(np.float64)))

    def is_finite(self) -> "Series":
        return self._unary_np(lambda a: np.isfinite(a.astype(np.float64)))

    def is_infinite(self) -> "Series":
        return self._unary_np(lambda a: np.isinf(a.astype(np.float64)))

    def has_nulls(self) -> bool:
        return bool(self._md_series.isna().any())

    def null_count(self) -> int:
        return int(self._md_series.isna().sum())

    # -- reductions ----------------------------------------------------- #

    def std(self, ddof: int = 1):
        return self._md_series.std(ddof=ddof)

    def var(self, ddof: int = 1):
        return self._md_series.var(ddof=ddof)

    def median(self):
        return self._md_series.median()

    def product(self):
        return self._md_series.prod()

    def quantile(self, quantile: float, interpolation: str = "nearest"):
        return self._md_series.quantile(quantile, interpolation=interpolation)

    def all(self, *, ignore_nulls: bool = True) -> bool:
        return bool(self._md_series.all())

    def any(self, *, ignore_nulls: bool = True) -> bool:
        return bool(self._md_series.any())

    def n_unique(self) -> int:
        return int(self._md_series.nunique(dropna=False))

    def skew(self, bias: bool = True):
        s = self._md_series._to_pandas()
        n = s.count()
        if n < 3:
            return None
        m = s - s.mean()
        g1 = (m**3).mean() / ((m**2).mean() ** 1.5)
        if bias:
            return float(g1)
        return float(g1 * np.sqrt(n * (n - 1)) / (n - 2))

    def kurtosis(self, *, fisher: bool = True, bias: bool = True):
        s = self._md_series._to_pandas()
        n = s.count()
        if n < 2:
            return None
        m = s - s.mean()
        g2 = (m**4).mean() / ((m**2).mean() ** 2)
        if not bias and n > 3:
            g2 = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 - 3 * (n - 1)) + 3
        return float(g2 - 3.0) if fisher else float(g2)

    def entropy(self, base: float = np.e, *, normalize: bool = True):
        p = self._md_series._to_pandas().to_numpy(dtype=np.float64)
        if normalize and p.sum() != 0:
            p = p / p.sum()
        with np.errstate(divide="ignore", invalid="ignore"):
            terms = np.where(p > 0, p * (np.log(p) / np.log(base)), 0.0)
        return float(-terms.sum())

    def nan_max(self):
        return self._md_series._to_pandas().max(skipna=False)

    def nan_min(self):
        return self._md_series._to_pandas().min(skipna=False)

    def lower_bound(self):
        dt = np.dtype(str(self.dtype))
        return np.iinfo(dt).min if dt.kind in "iu" else -np.inf

    def upper_bound(self):
        dt = np.dtype(str(self.dtype))
        return np.iinfo(dt).max if dt.kind in "iu" else np.inf

    # -- positions / order ---------------------------------------------- #

    def arg_max(self) -> int:
        return int(np.argmax(self.to_numpy()))

    def arg_min(self) -> int:
        return int(np.argmin(self.to_numpy()))

    def arg_sort(self, *, descending: bool = False) -> "Series":
        import modin_tpu.pandas as mpd

        order = np.argsort(self.to_numpy(), kind="stable")
        if descending:
            order = order[::-1]
        return Series(_md=mpd.Series(order, name=self.name))

    def arg_true(self) -> "Series":
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series(np.nonzero(self.to_numpy())[0], name=self.name))

    def arg_unique(self) -> "Series":
        import modin_tpu.pandas as mpd

        s = self._md_series._to_pandas().reset_index(drop=True)
        return Series(_md=mpd.Series(s.drop_duplicates(keep="first").index.to_numpy(), name=self.name))

    def search_sorted(self, element: Any, side: str = "any") -> Any:
        np_side = "left" if side in ("any", "left") else "right"
        result = np.searchsorted(self.to_numpy(), element, side=np_side)
        if np.ndim(result) == 0:
            return int(result)
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series(result, name=self.name))

    def is_sorted(self, *, descending: bool = False) -> bool:
        md = self._md_series
        return bool(
            md.is_monotonic_decreasing if descending else md.is_monotonic_increasing
        )

    def peak_max(self) -> "Series":
        s = self._md_series
        return Series(_md=(s > s.shift(1)).fillna(True) & (s > s.shift(-1)).fillna(True))

    def peak_min(self) -> "Series":
        s = self._md_series
        return Series(_md=(s < s.shift(1)).fillna(True) & (s < s.shift(-1)).fillna(True))

    # -- selection / reshaping ------------------------------------------ #

    def gather(self, indices: Any) -> "Series":
        idx = indices.to_list() if isinstance(indices, Series) else list(indices)
        return Series(_md=self._md_series.take(idx))

    def head(self, n: int = 10) -> "Series":
        return Series(_md=self._md_series.head(n))

    def tail(self, n: int = 10) -> "Series":
        return Series(_md=self._md_series.tail(n))

    def limit(self, n: int = 10) -> "Series":
        return self.head(n)

    def slice(self, offset: int, length: Optional[int] = None) -> "Series":
        stop = None if length is None else offset + length
        return Series(_md=self._md_series.iloc[offset:stop])

    def reverse(self) -> "Series":
        return Series(_md=self._md_series.iloc[::-1])

    def shuffle(self, seed: Optional[int] = None) -> "Series":
        return Series(_md=self._md_series.sample(frac=1.0, random_state=seed))

    def append(self, other: "Series") -> "Series":
        import modin_tpu.pandas as mpd

        return Series(
            _md=mpd.concat([self._md_series, other._md_series], ignore_index=True)
        )

    def extend_constant(self, value: Any, n: int) -> "Series":
        import modin_tpu.pandas as mpd

        return self.append(Series(_md=mpd.Series([value] * n)))

    def drop_nans(self) -> "Series":
        return Series(_md=self._md_series.dropna())

    def drop_nulls(self) -> "Series":
        return Series(_md=self._md_series.dropna())

    def scatter(self, indices: Any, values: Any) -> "Series":
        import modin_tpu.pandas as mpd

        # deep copy: _to_pandas may hand out read-only (device-cache) buffers
        s = self._md_series._to_pandas().reset_index(drop=True).copy(deep=True)
        idx = indices.to_list() if isinstance(indices, Series) else indices
        vals = values.to_list() if isinstance(values, Series) else values
        s.iloc[idx] = vals
        return Series(_md=mpd.Series(s))

    def set(self, filter: "Series", value: Any) -> "Series":
        mask = filter._md_series if isinstance(filter, Series) else filter
        return Series(_md=self._md_series.mask(mask, value))

    def zip_with(self, mask: "Series", other: "Series") -> "Series":
        return Series(
            _md=self._md_series.where(mask._md_series, other._md_series, axis=0)
        )

    def interpolate_by(self, by: "Series") -> "Series":
        import modin_tpu.pandas as mpd

        s = self._md_series._to_pandas().reset_index(drop=True)
        x = np.asarray(by.to_numpy(), dtype=np.float64)
        # np.interp requires monotonically increasing sample points: sort by
        # the by-column, interpolate, then scatter back to the input order
        order = np.argsort(x, kind="stable")
        xs = x[order]
        vals = s.to_numpy(dtype=np.float64)[order]
        valid = ~np.isnan(vals)
        out_sorted = np.interp(xs, xs[valid], vals[valid])
        out = np.empty_like(out_sorted)
        out[order] = out_sorted
        return Series(_md=mpd.Series(out, name=self.name))

    # -- membership / comparisons --------------------------------------- #

    def is_in(self, other: Any) -> "Series":
        vals = other.to_list() if isinstance(other, Series) else list(other)
        return Series(_md=self._md_series.isin(vals))

    def is_between(self, lower_bound: Any, upper_bound: Any, closed: str = "both") -> "Series":
        inclusive = {"both": "both", "left": "left", "right": "right", "none": "neither"}[closed]
        return Series(_md=self._md_series.between(lower_bound, upper_bound, inclusive=inclusive))

    def is_first_distinct(self) -> "Series":
        return Series(_md=~self._md_series.duplicated(keep="first"))

    def is_last_distinct(self) -> "Series":
        return Series(_md=~self._md_series.duplicated(keep="last"))

    def eq(self, other: Any) -> "Series":
        return self._wrap_op(other, lambda a, b: a == b)

    def ne(self, other: Any) -> "Series":
        return self._wrap_op(other, lambda a, b: a != b)

    def lt(self, other: Any) -> "Series":
        return self._wrap_op(other, lambda a, b: a < b)

    def le(self, other: Any) -> "Series":
        return self._wrap_op(other, lambda a, b: a <= b)

    def gt(self, other: Any) -> "Series":
        return self._wrap_op(other, lambda a, b: a > b)

    def ge(self, other: Any) -> "Series":
        return self._wrap_op(other, lambda a, b: a >= b)

    def eq_missing(self, other: Any) -> "Series":
        both_null = self.is_null() & (
            other.is_null() if isinstance(other, Series) else pandas.isna(other)
        )
        return (self.eq(other) | both_null).fill_null(False)

    def ne_missing(self, other: Any) -> "Series":
        return self.eq_missing(other).not_()

    def fill_null(self, value: Any = None) -> "Series":
        return Series(_md=self._md_series.fillna(value))

    def __and__(self, other):
        return self._wrap_op(other, lambda a, b: a & b)

    def __or__(self, other):
        return self._wrap_op(other, lambda a, b: a | b)

    def __invert__(self):
        return self.not_()

    def __ge__(self, other):
        return self._wrap_op(other, lambda a, b: a >= b)

    def __le__(self, other):
        return self._wrap_op(other, lambda a, b: a <= b)

    def __eq__(self, other):  # type: ignore[override]
        return self._wrap_op(other, lambda a, b: a == b)

    def __ne__(self, other):  # type: ignore[override]
        return self._wrap_op(other, lambda a, b: a != b)

    def __getitem__(self, key: Any):
        if isinstance(key, slice):
            return Series(_md=self._md_series.iloc[key])
        return self._md_series.iloc[key]

    # -- windows / cumulatives ------------------------------------------ #

    def cum_sum(self, *, reverse: bool = False) -> "Series":
        return self._cumulative("cumsum", reverse)

    def cum_max(self, *, reverse: bool = False) -> "Series":
        return self._cumulative("cummax", reverse)

    def cum_min(self, *, reverse: bool = False) -> "Series":
        return self._cumulative("cummin", reverse)

    def cum_prod(self, *, reverse: bool = False) -> "Series":
        return self._cumulative("cumprod", reverse)

    def cum_count(self, *, reverse: bool = False) -> "Series":
        counted = self.is_not_null()._md_series.astype("int64")
        if reverse:
            return Series(_md=counted.iloc[::-1].cumsum().iloc[::-1])
        return Series(_md=counted.cumsum())

    def _cumulative(self, op: str, reverse: bool) -> "Series":
        md = self._md_series
        if reverse:
            return Series(_md=getattr(md.iloc[::-1], op)().iloc[::-1])
        return Series(_md=getattr(md, op)())

    def cumulative_eval(self, expr: Any, *args: Any, **kwargs: Any) -> "Series":
        raise NotImplementedError("cumulative_eval requires polars expressions")

    def diff(self, n: int = 1, null_behavior: str = "ignore") -> "Series":
        result = self._md_series.diff(n)
        if null_behavior == "drop":
            result = result.dropna()
        return Series(_md=result)

    def pct_change(self, n: int = 1) -> "Series":
        return Series(_md=self._md_series.pct_change(n))

    def shift(self, n: int = 1, *, fill_value: Any = None) -> "Series":
        return Series(_md=self._md_series.shift(n, fill_value=fill_value))

    def rank(self, method: str = "average", *, descending: bool = False) -> "Series":
        pd_method = {"average": "average", "min": "min", "max": "max", "dense": "dense", "ordinal": "first"}[method]
        return Series(_md=self._md_series.rank(method=pd_method, ascending=not descending))

    def _rolling(self, op: str, window_size: int, *args: Any, **kwargs: Any) -> "Series":
        min_samples = kwargs.pop("min_samples", None) or window_size
        roller = self._md_series.rolling(window_size, min_periods=min_samples)
        return Series(_md=getattr(roller, op)(*args, **kwargs))

    def rolling_sum(self, window_size: int, **kwargs: Any) -> "Series":
        return self._rolling("sum", window_size, **kwargs)

    def rolling_mean(self, window_size: int, **kwargs: Any) -> "Series":
        return self._rolling("mean", window_size, **kwargs)

    def rolling_min(self, window_size: int, **kwargs: Any) -> "Series":
        return self._rolling("min", window_size, **kwargs)

    def rolling_max(self, window_size: int, **kwargs: Any) -> "Series":
        return self._rolling("max", window_size, **kwargs)

    def rolling_std(self, window_size: int, ddof: int = 1, **kwargs: Any) -> "Series":
        return self._rolling("std", window_size, ddof=ddof, **kwargs)

    def rolling_var(self, window_size: int, ddof: int = 1, **kwargs: Any) -> "Series":
        return self._rolling("var", window_size, ddof=ddof, **kwargs)

    def rolling_median(self, window_size: int, **kwargs: Any) -> "Series":
        return self._rolling("median", window_size, **kwargs)

    def rolling_skew(self, window_size: int, **kwargs: Any) -> "Series":
        return self._rolling("skew", window_size, **kwargs)

    def rolling_quantile(self, quantile: float, interpolation: str = "nearest", window_size: int = 2, **kwargs: Any) -> "Series":
        return self._rolling("quantile", window_size, quantile, interpolation=interpolation, **kwargs)

    def rolling_map(self, function: Any, window_size: int, **kwargs: Any) -> "Series":
        min_samples = kwargs.pop("min_samples", None) or window_size
        roller = self._md_series.rolling(window_size, min_periods=min_samples)
        return Series(_md=roller.apply(function))

    def ewm_mean(self, com: Any = None, span: Any = None, half_life: Any = None, alpha: Any = None, *, adjust: bool = True, min_samples: int = 1, ignore_nulls: bool = False, **kwargs: Any) -> "Series":
        ewm = self._md_series.ewm(com=com, span=span, halflife=half_life, alpha=alpha, adjust=adjust, min_periods=min_samples, ignore_na=ignore_nulls)
        return Series(_md=ewm.mean())

    def ewm_std(self, com: Any = None, span: Any = None, half_life: Any = None, alpha: Any = None, *, adjust: bool = True, bias: bool = False, min_samples: int = 1, ignore_nulls: bool = False, **kwargs: Any) -> "Series":
        ewm = self._md_series.ewm(com=com, span=span, halflife=half_life, alpha=alpha, adjust=adjust, min_periods=min_samples, ignore_na=ignore_nulls)
        return Series(_md=ewm.std(bias=bias))

    def ewm_var(self, com: Any = None, span: Any = None, half_life: Any = None, alpha: Any = None, *, adjust: bool = True, bias: bool = False, min_samples: int = 1, ignore_nulls: bool = False, **kwargs: Any) -> "Series":
        ewm = self._md_series.ewm(com=com, span=span, halflife=half_life, alpha=alpha, adjust=adjust, min_periods=min_samples, ignore_na=ignore_nulls)
        return Series(_md=ewm.var(bias=bias))

    def ewm_mean_by(self, by: Any, *, half_life: Any) -> "Series":
        raise NotImplementedError("ewm_mean_by requires event-time decay")

    # -- distinct / binning --------------------------------------------- #

    def value_counts(self, *, sort: bool = False, name: str = "count") -> "DataFrame":
        vc = self._md_series.value_counts(sort=sort, dropna=False)
        out = vc.reset_index()
        out.columns = [self.name or "", name]
        return DataFrame._from_md(out)

    def unique_counts(self) -> "Series":
        return Series(_md=self._md_series.value_counts(sort=False))

    def mode(self) -> "Series":
        return Series(_md=self._md_series.mode())

    def rle_id(self) -> "Series":
        s = self._md_series
        changed = s.ne(s.shift(1)).fillna(True)
        return Series(_md=changed.astype("int64").cumsum() - 1)

    def rle(self) -> "DataFrame":
        import modin_tpu.pandas as mpd

        s = self._md_series._to_pandas().reset_index(drop=True)
        changed = s.ne(s.shift(1)).fillna(True)
        run_id = changed.cumsum()
        lengths = run_id.value_counts(sort=False).sort_index()
        values = s[changed.to_numpy()]
        return DataFrame._from_md(
            mpd.DataFrame({"len": lengths.to_numpy(), "value": values.to_numpy()})
        )

    def cut(self, breaks: Any, *, labels: Any = None, left_closed: bool = False) -> "Series":
        # polars breaks are INTERIOR split points (implicit +/-inf bounds);
        # pandas.cut wants the complete edge list
        edges = [-np.inf, *list(breaks), np.inf]
        result = pandas.cut(
            self._md_series._to_pandas(), edges, labels=labels, right=not left_closed
        )
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series(result.astype(str), name=self.name))

    def qcut(self, quantiles: Any, *, labels: Any = None) -> "Series":
        if isinstance(quantiles, int):
            q = quantiles
        else:
            # polars quantiles are interior probabilities; close the range
            q = [0.0, *list(quantiles), 1.0]
        result = pandas.qcut(self._md_series._to_pandas(), q, labels=labels)
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series(result.astype(str), name=self.name))

    def hist(self, bins: Any = None, *, bin_count: Optional[int] = None) -> "DataFrame":
        import modin_tpu.pandas as mpd

        data = self._md_series._to_pandas().dropna().to_numpy(dtype=np.float64)
        counts, edges = np.histogram(
            data, bins=bins if bins is not None else (bin_count or 10)
        )
        return DataFrame._from_md(
            mpd.DataFrame({"breakpoint": edges[1:], "count": counts})
        )

    def describe(self) -> "DataFrame":
        import modin_tpu.pandas as mpd

        desc = self._md_series._to_pandas().describe()
        return DataFrame._from_md(
            mpd.DataFrame({"statistic": desc.index.to_numpy(), "value": desc.to_numpy()})
        )

    # -- remapping ------------------------------------------------------ #

    def replace(self, old: Any, new: Any = None) -> "Series":
        mapping = old if isinstance(old, dict) else dict(zip(np.atleast_1d(old), np.atleast_1d(new)))
        md = self._md_series
        return Series(_md=md.map(lambda v: mapping.get(v, v)))

    def replace_strict(self, old: Any, new: Any = None, *, default: Any = None) -> "Series":
        mapping = old if isinstance(old, dict) else dict(zip(np.atleast_1d(old), np.atleast_1d(new)))
        md = self._md_series
        return Series(_md=md.map(lambda v: mapping.get(v, default)))

    def map_elements(self, function: Any, return_dtype: Any = None) -> "Series":
        result = self._md_series.map(function)
        if return_dtype is not None:
            result = result.astype(return_dtype)
        return Series(_md=result)

    def hash(self, seed: int = 0, **kwargs: Any) -> "Series":
        import modin_tpu.pandas as mpd

        hashed = pandas.util.hash_pandas_object(
            self._md_series._to_pandas().reset_index(drop=True), index=False
        )
        return Series(_md=mpd.Series(hashed.to_numpy(), name=self.name))

    def implode(self) -> "Series":
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series([self.to_list()], name=self.name))

    # -- accessor namespaces -------------------------------------------- #

    @property
    def str(self) -> "_PolarsStrNamespace":
        return _PolarsStrNamespace(self)

    @property
    def dt(self) -> "_PolarsDtNamespace":
        return _PolarsDtNamespace(self)

    @property
    def cat(self) -> "_PolarsCatNamespace":
        return _PolarsCatNamespace(self)


for _name, _value in vars(_SeriesMethods).items():
    if not _name.startswith("_") or _name in ("_unary_np", "_cumulative", "_rolling"):
        setattr(Series, _name, _value)
for _dunder in ("__and__", "__or__", "__invert__", "__ge__", "__le__", "__eq__", "__ne__", "__getitem__"):
    setattr(Series, _dunder, vars(_SeriesMethods)[_dunder])
Series.__hash__ = None


class _PolarsStrNamespace:
    """polars ``Series.str`` verbs over the pandas str accessor."""

    def __init__(self, series: Series) -> None:
        self._s = series

    def _map(self, fn) -> Series:
        return Series(_md=fn(self._s._md_series.str))

    def to_uppercase(self) -> Series:
        return self._map(lambda s: s.upper())

    def to_lowercase(self) -> Series:
        return self._map(lambda s: s.lower())

    def to_titlecase(self) -> Series:
        return self._map(lambda s: s.title())

    def len_chars(self) -> Series:
        return self._map(lambda s: s.len())

    def contains(self, pattern: str, *, literal: bool = False) -> Series:
        return self._map(lambda s: s.contains(pattern, regex=not literal))

    def starts_with(self, prefix: str) -> Series:
        return self._map(lambda s: s.startswith(prefix))

    def ends_with(self, suffix: str) -> Series:
        return self._map(lambda s: s.endswith(suffix))

    def strip_chars(self, characters: Optional[str] = None) -> Series:
        return self._map(lambda s: s.strip(characters))

    def replace_all(self, pattern: str, value: str, *, literal: bool = False) -> Series:
        return self._map(lambda s: s.replace(pattern, value, regex=not literal))

    def slice(self, offset: int, length: Optional[int] = None) -> Series:
        stop = None if length is None else offset + length
        return self._map(lambda s: s.slice(offset, stop))

    def split(self, by: str) -> Series:
        return self._map(lambda s: s.split(by))

    def zfill(self, length: int) -> Series:
        return self._map(lambda s: s.zfill(length))


class _PolarsDtNamespace:
    """polars ``Series.dt`` verbs over the pandas dt accessor."""

    def __init__(self, series: Series) -> None:
        self._s = series

    def _prop(self, name: str) -> Series:
        return Series(_md=getattr(self._s._md_series.dt, name))

    def year(self) -> Series:
        return self._prop("year")

    def month(self) -> Series:
        return self._prop("month")

    def day(self) -> Series:
        return self._prop("day")

    def hour(self) -> Series:
        return self._prop("hour")

    def minute(self) -> Series:
        return self._prop("minute")

    def second(self) -> Series:
        return self._prop("second")

    def ordinal_day(self) -> Series:
        return self._prop("dayofyear")

    def weekday(self) -> Series:
        # polars: Monday=1 .. Sunday=7; pandas: Monday=0
        return Series(_md=self._s._md_series.dt.dayofweek + 1)

    def date(self) -> Series:
        return self._prop("date")

    def strftime(self, format: str) -> Series:
        return Series(_md=self._s._md_series.dt.strftime(format))


class _PolarsCatNamespace:
    """polars ``Series.cat`` verbs over the pandas cat accessor."""

    def __init__(self, series: Series) -> None:
        self._s = series

    def get_categories(self) -> Series:
        import modin_tpu.pandas as mpd

        return Series(_md=mpd.Series(self._s._md_series.cat.categories.to_numpy()))


# ---------------------------------------------------------------------- #
# GroupBy surface expansion (ref modin/polars/groupby.py: 17 methods)
# ---------------------------------------------------------------------- #


class _GroupByMethods:
    def median(self) -> DataFrame:
        return self._simple("median")

    def n_unique(self) -> DataFrame:
        md = self._df._md
        result = md.groupby(self._keys, sort=True).nunique()
        return DataFrame._from_md(result.reset_index())

    def first(self) -> DataFrame:
        return self._simple("first")

    def last(self) -> DataFrame:
        return self._simple("last")

    def quantile(self, quantile: float, interpolation: str = "nearest") -> DataFrame:
        md = self._df._md
        result = md.groupby(self._keys, sort=True).quantile(
            quantile, interpolation=interpolation
        )
        return DataFrame._from_md(result.reset_index())

    def head(self, n: int = 5) -> DataFrame:
        md = self._df._md
        return DataFrame._from_md(
            md.groupby(self._keys, sort=False).head(n).reset_index(drop=True)
        )

    def tail(self, n: int = 5) -> DataFrame:
        md = self._df._md
        return DataFrame._from_md(
            md.groupby(self._keys, sort=False).tail(n).reset_index(drop=True)
        )

    def all(self) -> DataFrame:
        md = self._df._md
        value_cols = [c for c in md.columns if c not in self._keys]
        result = md.groupby(self._keys, sort=True)[value_cols].agg(list)
        return DataFrame._from_md(result.reset_index())

    def map_groups(self, function: Any) -> DataFrame:
        md = self._df._md
        pieces = [
            function(DataFrame._from_md(part.reset_index(drop=True)))
            for _key, part in md.groupby(self._keys, sort=True)
        ]
        import modin_tpu.pandas as mpd

        return DataFrame._from_md(
            mpd.concat([p._md for p in pieces], ignore_index=True)
        )


for _name, _value in vars(_GroupByMethods).items():
    if not _name.startswith("_"):
        setattr(GroupBy, _name, _value)


# ---------------------------------------------------------------------- #
# DataFrame surface expansion (ref modin/polars/dataframe.py long tail)
# ---------------------------------------------------------------------- #


class _DataFrameMethods:
    def select_seq(self, *exprs: Any, **named_exprs: Any) -> "DataFrame":
        return self.select(*exprs, **named_exprs)

    def with_columns_seq(self, *exprs: Any, **named_exprs: Any) -> "DataFrame":
        return self.with_columns(*exprs, **named_exprs)

    def with_row_index(self, name: str = "index", offset: int = 0) -> "DataFrame":
        md = self._md.copy()
        md.insert(0, name, np.arange(offset, offset + len(md), dtype=np.uint32))
        return DataFrame._from_md(md)

    def melt(self, id_vars: Any = None, value_vars: Any = None, variable_name: Optional[str] = None, value_name: Optional[str] = None) -> "DataFrame":
        return DataFrame._from_md(
            self._md.melt(
                id_vars=id_vars, value_vars=value_vars,
                var_name=variable_name or "variable",
                value_name=value_name or "value",
            )
        )

    def unpivot(self, on: Any = None, *, index: Any = None, variable_name: Optional[str] = None, value_name: Optional[str] = None) -> "DataFrame":
        return self.melt(id_vars=index, value_vars=on, variable_name=variable_name, value_name=value_name)

    def approx_n_unique(self) -> "DataFrame":
        counts = {c: [int(self._md[c].nunique(dropna=False))] for c in self._md.columns}
        import modin_tpu.pandas as mpd

        return DataFrame._from_md(mpd.DataFrame(counts))

    def collect_schema(self) -> dict:
        return self.schema

    def glimpse(self, *, return_as_string: bool = False) -> Optional[str]:
        lines = [f"Rows: {len(self._md)}", f"Columns: {len(self._md.columns)}"]
        head = self._md.head(10)._to_pandas()
        for c in head.columns:
            vals = ", ".join(repr(v) for v in head[c].tolist()[:5])
            lines.append(f"$ {c} <{head[c].dtype}> {vals}")
        text = "\n".join(lines)
        if return_as_string:
            return text
        print(text)
        return None

    def to_init_repr(self, n: int = 1000) -> str:
        head = self._md.head(n)._to_pandas()
        cols = ", ".join(
            f"pl.Series({c!r}, {head[c].tolist()!r})" for c in head.columns
        )
        return f"pl.DataFrame([{cols}])"

    def merge_sorted(self, other: "DataFrame", key: str) -> "DataFrame":
        import modin_tpu.pandas as mpd

        merged = mpd.concat([self._md, other._md], ignore_index=True)
        return DataFrame._from_md(
            merged.sort_values(key, kind="stable").reset_index(drop=True)
        )

    def update(self, other: "DataFrame", on: Any = None, how: str = "left") -> "DataFrame":
        import modin_tpu.pandas as mpd

        # deep copy: _to_pandas may hand out read-only (device-cache) buffers
        pdf = self._md._to_pandas().copy(deep=True)
        opdf = other._md._to_pandas()
        if on is not None:
            pdf = pdf.set_index(on)
            opdf = opdf.set_index(on)
        pdf.update(opdf)
        if on is not None:
            if how == "inner":
                pdf = pdf.loc[pdf.index.intersection(opdf.index)]
            elif how == "full":
                extra = opdf.loc[opdf.index.difference(pdf.index)]
                pdf = pandas.concat([pdf, extra]).sort_index()
            pdf = pdf.reset_index()
        return DataFrame._from_md(mpd.DataFrame(pdf))

    def hash_rows(self, seed: int = 0, **kwargs: Any) -> "Series":
        import modin_tpu.pandas as mpd

        hashed = pandas.util.hash_pandas_object(
            self._md._to_pandas().reset_index(drop=True), index=False
        )
        return Series(_md=mpd.Series(hashed.to_numpy(), name=""))

    def iter_slices(self, n_rows: int = 10000):
        for start in range(0, len(self._md), n_rows):
            yield DataFrame._from_md(
                self._md.iloc[start:start + n_rows].reset_index(drop=True)
            )

    def iter_rows(self, *, named: bool = False):
        return iter(self.rows(named=named))

    def join_asof(self, other: "DataFrame", *, on: Any = None, left_on: Any = None, right_on: Any = None, by: Any = None, strategy: str = "backward", suffix: str = "_right") -> "DataFrame":
        import modin_tpu.pandas as mpd

        direction = {"backward": "backward", "forward": "forward", "nearest": "nearest"}[strategy]
        left = self._md._to_pandas()
        right = other._md._to_pandas()
        merged = pandas.merge_asof(
            left, right,
            on=on, left_on=left_on, right_on=right_on, by=by,
            direction=direction, suffixes=("", suffix),
        )
        return DataFrame._from_md(mpd.DataFrame(merged))

    def sql(self, query: str, *, table_name: str = "self") -> "DataFrame":
        from modin_tpu.experimental import sql as _sql

        return DataFrame._from_md(_sql.query(query, **{table_name: self._md}))

    def map_rows(self, function: Any) -> "DataFrame":
        import modin_tpu.pandas as mpd

        rows = [function(r) for r in self.rows()]
        if rows and isinstance(rows[0], tuple):
            out = mpd.DataFrame(rows, columns=[f"column_{i}" for i in range(len(rows[0]))])
        else:
            out = mpd.DataFrame({"map": rows})
        return DataFrame._from_md(out)

    def rows_by_key(self, key: Any, *, named: bool = False, unique: bool = False) -> dict:
        keys = [key] if isinstance(key, str) else list(key)
        out: dict = {}
        for row in self.rows(named=True):
            k = tuple(row[c] for c in keys)
            k = k[0] if len(keys) == 1 else k
            val = row if named else tuple(v for c, v in row.items() if c not in keys)
            if unique:
                out[k] = val
            else:
                out.setdefault(k, []).append(val)
        return out

    def serialize(self, file: Any = None):
        import pickle

        payload = pickle.dumps(self._md._to_pandas())
        if file is None:
            return payload
        if hasattr(file, "write"):
            file.write(payload)
        else:
            with open(file, "wb") as fh:
                fh.write(payload)
        return None

    @classmethod
    def deserialize(cls, source: Any) -> "DataFrame":
        import pickle

        import modin_tpu.pandas as mpd

        if hasattr(source, "read"):
            payload = source.read()
        elif isinstance(source, (bytes, bytearray)):
            payload = bytes(source)
        else:
            with open(source, "rb") as fh:
                payload = fh.read()
        return DataFrame._from_md(mpd.DataFrame(pickle.loads(payload)))

    def set_sorted(self, column: str, *, descending: bool = False) -> "DataFrame":
        return self

    def rechunk(self) -> "DataFrame":
        return self

    def unnest(self, columns: Any) -> "DataFrame":
        cols = [columns] if isinstance(columns, str) else list(columns)
        import modin_tpu.pandas as mpd

        pdf = self._md._to_pandas()
        pieces = []
        for c in pdf.columns:
            if c in cols:
                expanded = pandas.json_normalize(pdf[c])
                expanded.index = pdf.index
                pieces.append(expanded)
            else:
                pieces.append(pdf[[c]])
        return DataFrame._from_md(mpd.DataFrame(pandas.concat(pieces, axis=1)))


for _name, _value in vars(_DataFrameMethods).items():
    if not _name.startswith("_"):
        setattr(DataFrame, _name, _value)
