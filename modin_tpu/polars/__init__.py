"""``modin_tpu.polars`` — polars-flavored API over the device query compilers.

Reference design: modin/polars/ (4,555 LoC).
"""

from modin_tpu.polars.dataframe import DataFrame, Expr, GroupBy, Series, col, lit  # noqa: F401
from modin_tpu.polars.lazyframe import LazyFrame  # noqa: F401


def from_pandas(df):
    """Build a polars-flavored frame from a pandas or modin_tpu frame."""
    return DataFrame(df)


def read_csv(path, **kwargs):
    """Polars-flavored read_csv through the parallel dispatcher."""
    import modin_tpu.pandas as mpd

    return DataFrame(mpd.read_csv(path, **kwargs))


def concat(items, how: str = "vertical"):
    import modin_tpu.pandas as mpd

    axis = 0 if how in ("vertical", "diagonal") else 1
    return DataFrame(
        mpd.concat([i._md for i in items], axis=axis, ignore_index=axis == 0)
    )
