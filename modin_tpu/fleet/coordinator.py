"""graftfleet coordinator: replica supervision, routing, drain & respawn.

One ``Coordinator`` instance per fleet-enabled process.  It owns:

- **the replica table** — N supervised ``python -m modin_tpu.fleet.replica``
  processes, each announced via a hello on the coordinator's control
  listener and tracked ``(pid, generation, rpc_port, watch_port,
  last_heartbeat, shed_rate, latencies)``;
- **routing** — tenants are sticky-assigned to replicas; a new tenant
  lands on the survivor with the lowest (shed_rate, assigned-tenant)
  load, and every query is dispatched connection-per-request over the
  wire protocol with the *remaining* deadline riding along;
- **failure detection**, three independent ways: the supervised process
  exits (``proc.poll``), its heartbeats go silent past ~3 intervals and
  a fresh liveness probe times out (the SIGSTOP-hang case: socket alive,
  process wedged), or a dispatch hits a dead socket (connect refused /
  reset / closed mid-frame);
- **loss handling** — the lost replica is SIGKILLed (a stopped process
  must not wake up and serve stale state), its in-flight queries are
  interrupted (their joins poll replica state every timeout tick) and
  re-dispatched to a survivor when idempotent-by-lineage, its tenants
  drain and redistribute weighted-fair with each survivor's typed-shed
  rate as the backpressure signal, and — with ``MODIN_TPU_FLEET_RESPAWN``
  on — a fresh generation respawns and re-warms from the dataset
  manifest plus a healthy survivor's exported graftview artifacts.

Nothing ever joins unboundedly: a query with a deadline aborts typed at
its deadline, and a query without one is capped by the global join
watchdog (:data:`JOIN_WATCHDOG_S`) — the fleet's "never a hang" half of
the serving contract.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from modin_tpu.concurrency import named_lock, named_rlock
from modin_tpu.fleet import wire
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import meters as graftmeter
from modin_tpu.observability import spans as graftscope
from modin_tpu.serving.errors import DeadlineExceeded, QueryRejected

#: Global join watchdog (seconds) for queries submitted WITHOUT a
#: deadline: the hard cap on one routed query's join, so a wedged replica
#: can never hang a caller that asked for no budget.
JOIN_WATCHDOG_S = 60.0

#: How long a respawned replica gets to say hello before the attempt is
#: abandoned and retried (imports + mesh build dominate this).
_HELLO_TIMEOUT_S = 60.0

#: Poll tick for interruptible joins (state/deadline checks while blocked).
_POLL_S = 0.25


class _DeadSocket(ConnectionError):
    """Internal: the replica's socket died under a dispatch."""


def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    idx = min(int(q * len(ordered)), len(ordered) - 1)
    return ordered[idx]


class _Replica:
    """Supervision record for one replica slot (index is stable across
    generations; everything else belongs to the current generation)."""

    def __init__(self, index: int):
        from modin_tpu import fleet as _fleet

        _fleet._note_alloc()
        self.index = index
        self.generation = 0
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.rpc_port: Optional[int] = None
        self.watch_port: int = -1
        self.state = "spawning"  # spawning | up | lost | respawning | stopped
        self.last_heartbeat = 0.0
        self.shed_rate = 0.0
        self.heartbeat_counters: dict = {}
        self.hello_event = threading.Event()
        self.latencies: deque = deque(maxlen=512)
        self.inflight_socks: set = set()
        self.lock = named_lock("fleet.replica_state")

    def note_inflight(self, sock: socket.socket) -> None:
        with self.lock:
            self.inflight_socks.add(sock)

    def forget_inflight(self, sock: socket.socket) -> None:
        with self.lock:
            self.inflight_socks.discard(sock)

    def interrupt_inflight(self) -> None:
        """Close every in-flight dispatch socket: blocked joins on this
        replica fail over NOW instead of at their next poll tick."""
        with self.lock:
            socks = list(self.inflight_socks)
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass


class Coordinator:
    """The fleet control plane (see module docstring)."""

    def __init__(self, replicas: Optional[int] = None,
                 durability_dir: Optional[str] = None):
        from modin_tpu import fleet as _fleet
        from modin_tpu.config import FleetDurabilityDir, FleetReplicas

        _fleet._note_alloc()
        count = int(replicas if replicas is not None else FleetReplicas.get())
        #: graftwal root the replicas recover durable feeds from on warm-up
        #: (spawn env + respawn) — '' disables durability in the fleet
        self._durability_dir = str(
            durability_dir if durability_dir is not None
            else FleetDurabilityDir.get()
        )
        self._lock = named_rlock("fleet.coordinator")
        self._replicas = [_Replica(i) for i in range(count)]
        self._assignments: Dict[str, int] = {}  # tenant -> replica index
        self._listener: Optional[socket.socket] = None
        self._control_port: Optional[int] = None
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._obs_span_stack: Any = None
        self._obs_scopes: Any = None
        self.routed = 0
        self.redispatched = 0
        self.lost_count = 0
        self.respawned_count = 0
        self.redistributed_count = 0
        self.respawn_failures = 0
        self._test_crash_next_respawn = False

    # -- lifecycle ------------------------------------------------------- #

    def start(self) -> None:
        # service threads adopt the starter's observability context so
        # their fleet.* metrics bill whoever brought the fleet up
        self._obs_span_stack = graftscope.snapshot_stack()
        self._obs_scopes = graftmeter.snapshot_scopes()
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.bind(("127.0.0.1", 0))
        listener.listen(64)
        self._listener = listener
        self._control_port = listener.getsockname()[1]
        accept = threading.Thread(
            target=self._accept_loop, name="modin-tpu-fleet-accept",
            daemon=True,
        )
        accept.start()
        self._threads.append(accept)
        for rep in self._replicas:
            self._spawn(rep)
        deadline = time.monotonic() + _HELLO_TIMEOUT_S
        for rep in self._replicas:
            remaining = max(deadline - time.monotonic(), 0.1)
            if not rep.hello_event.wait(remaining):
                raise RuntimeError(
                    f"fleet replica {rep.index} never said hello "
                    f"(pid {rep.pid})"
                )
        monitor = threading.Thread(
            target=self._monitor_loop, name="modin-tpu-fleet-monitor",
            daemon=True,
        )
        monitor.start()
        self._threads.append(monitor)

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            reps = list(self._replicas)
        for rep in reps:
            rep.state = "stopped"
            rep.interrupt_inflight()
            if rep.pid is not None:
                try:
                    os.kill(rep.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
            if rep.proc is not None:
                try:
                    rep.proc.wait(timeout=10)
                except Exception:
                    pass
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    # -- spawn / hello / heartbeats -------------------------------------- #

    def _spawn(self, rep: _Replica) -> None:
        import modin_tpu

        env = dict(os.environ)
        # the replica must import the coordinator's modin_tpu, wherever it
        # came from (source checkout or install), regardless of child cwd
        import_root = os.path.dirname(os.path.dirname(modin_tpu.__file__))
        env["PYTHONPATH"] = (
            import_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else import_root
        )
        env["MODIN_TPU_FLEET"] = "0"  # replicas never nest fleets
        env["MODIN_TPU_SERVING"] = "1"
        env["MODIN_TPU_WATCH"] = "1"  # per-replica SLO attribution
        # the fixed-port collision fix: whatever MODIN_TPU_WATCH_PORT the
        # coordinator's environment pins, every replica binds ephemeral
        # and reports the live port back in hello/heartbeats
        env["MODIN_TPU_WATCH_PORT"] = "0"
        env["MODIN_TPU_FLEET_COORD"] = f"127.0.0.1:{self._control_port}"
        env["MODIN_TPU_FLEET_INDEX"] = str(rep.index)
        env["MODIN_TPU_FLEET_GEN"] = str(rep.generation)
        # both sides must agree on the heartbeat cadence even when it was
        # configured by put() rather than the environment
        env["MODIN_TPU_FLEET_HEARTBEAT_S"] = str(self._heartbeat_s())
        if self._durability_dir:
            # graftwal: the replica recovers its durable feeds (checkpoint
            # + WAL-tail replay) from this root during warm-up
            env["MODIN_TPU_FLEET_DURABILITY_DIR"] = self._durability_dir
            env["MODIN_TPU_INGEST"] = "1"
        else:
            env.pop("MODIN_TPU_FLEET_DURABILITY_DIR", None)
        if self._test_crash_next_respawn:
            env["MODIN_TPU_FLEET_TEST_CRASH"] = "warm"
            self._test_crash_next_respawn = False
        else:
            env.pop("MODIN_TPU_FLEET_TEST_CRASH", None)
        rep.hello_event.clear()
        rep.proc = subprocess.Popen(
            [sys.executable, "-m", "modin_tpu.fleet.replica"], env=env
        )
        rep.pid = rep.proc.pid
        emit_metric("fleet.replica.spawn", 1)

    def _accept_loop(self) -> None:
        graftscope.seed_thread(self._obs_span_stack)
        graftmeter.seed_thread_scopes(self._obs_scopes)
        try:
            while not self._stop.is_set():
                try:
                    conn, _addr = self._listener.accept()
                except OSError:
                    return
                threading.Thread(
                    target=self._control_reader, args=(conn,),
                    name="modin-tpu-fleet-control", daemon=True,
                ).start()
        finally:
            graftmeter.seed_thread_scopes(None)
            graftscope.seed_thread(None)

    def _control_reader(self, conn: socket.socket) -> None:
        """One replica's control stream: a hello, then heartbeats."""
        graftscope.seed_thread(self._obs_span_stack)
        graftmeter.seed_thread_scopes(self._obs_scopes)
        rep: Optional[_Replica] = None
        try:
            conn.settimeout(30.0)
            hello = wire.recv_msg(conn)
            if hello.get("type") != "hello":
                return
            with self._lock:
                index = int(hello["index"])
                if not 0 <= index < len(self._replicas):
                    return
                rep = self._replicas[index]
                if int(hello["generation"]) != rep.generation:
                    return  # a stale generation's hello; its process is dead
                rep.rpc_port = int(hello["rpc_port"])
                rep.watch_port = int(hello["watch_port"])
                rep.pid = int(hello["pid"])
                rep.last_heartbeat = time.monotonic()
                if rep.state == "spawning":
                    # first generation goes routable at hello; a RESPAWN
                    # stays "respawning" until its warm RPC succeeds
                    rep.state = "up"
            rep.hello_event.set()
            conn.settimeout(None)
            while not self._stop.is_set():
                beat = wire.recv_msg(conn)
                if beat.get("type") != "heartbeat":
                    continue
                if int(beat.get("generation", -1)) != rep.generation:
                    return  # a SIGCONT-resumed corpse; its successor owns the slot
                rep.last_heartbeat = time.monotonic()
                rep.shed_rate = float(beat.get("shed_rate", 0.0))
                rep.watch_port = int(beat.get("watch_port", rep.watch_port))
                rep.heartbeat_counters = {
                    k: beat[k]
                    for k in ("running", "shed", "admitted", "completed")
                    if k in beat
                }
        except wire.WireError:
            pass  # silence: the monitor's heartbeat-age leg takes over
        finally:
            try:
                conn.close()
            except OSError:
                pass
            graftmeter.seed_thread_scopes(None)
            graftscope.seed_thread(None)

    # -- datasets -------------------------------------------------------- #

    def register_dataset(
        self, name: str, reader: str, args: tuple = (),
        kwargs: Optional[dict] = None,
    ) -> None:
        """Record the manifest entry and warm it onto every live replica."""
        from modin_tpu.core.execution import recovery

        recovery.register_dataset(name, reader, args, kwargs)
        entry = [
            e for e in recovery.dataset_manifest() if e["name"] == str(name)
        ]
        for rep in self._up_replicas():
            try:
                reply = self._call(
                    rep,
                    {"type": "warm", "manifest": entry, "views": {}},
                    timeout=JOIN_WATCHDOG_S,
                )
            except (_DeadSocket, DeadlineExceeded):
                # A replica dying (or wedging past the watchdog) mid-warm is
                # a supervision event, not a registration failure: the
                # manifest entry is already recorded, so the respawn path
                # re-warms the slot from it.  Registration never leaks the
                # internal dead-socket signal to the caller.
                self._declare_lost(rep, "dead_socket")
                continue
            if not reply.get("ok"):
                raise RuntimeError(
                    f"replica {rep.index} failed to warm {name!r}: "
                    f"{reply.get('message')}"
                )

    # -- dispatch -------------------------------------------------------- #

    def _up_replicas(self) -> List[_Replica]:
        with self._lock:
            return [r for r in self._replicas if r.state == "up"]

    def _route(self, tenant: str) -> _Replica:
        with self._lock:
            idx = self._assignments.get(tenant)
            if idx is not None:
                rep = self._replicas[idx]
                if rep.state == "up":
                    return rep
            up = [r for r in self._replicas if r.state == "up"]
            if not up:
                raise QueryRejected(
                    f"no live replicas to route tenant {tenant!r}",
                    reason="no_replicas",
                    retry_after_s=self._heartbeat_s() * 3,
                )
            loads: Dict[int, int] = {}
            for t, i in self._assignments.items():
                loads[i] = loads.get(i, 0) + 1
            rep = min(
                up,
                key=lambda r: (
                    (loads.get(r.index, 0) + 1) * (1.0 + r.shed_rate),
                    r.index,
                ),
            )
            self._assignments[tenant] = rep.index
            return rep

    @staticmethod
    def _heartbeat_s() -> float:
        from modin_tpu.config import FleetHeartbeatS

        return float(FleetHeartbeatS.get())

    def _call(
        self,
        rep: _Replica,
        msg: dict,
        timeout: float,
        deadline_t: Optional[float] = None,
        track: bool = False,
    ) -> dict:
        """One connection-per-request RPC with an interruptible join.

        The join polls every :data:`_POLL_S`: replica declared lost ->
        :class:`_DeadSocket`; caller deadline passed -> typed
        :class:`DeadlineExceeded`; watchdog passed -> the same, tagged
        ``fleet.watchdog``.  Dead sockets at ANY stage (connect, send,
        recv) raise :class:`_DeadSocket` for the caller's failover.
        """
        watchdog_t = time.monotonic() + timeout
        generation = rep.generation
        try:
            sock = wire.connect("127.0.0.1", rep.rpc_port, timeout=2.0)
        except OSError as err:
            raise _DeadSocket(f"connect to replica {rep.index}: {err}") from err
        if track:
            rep.note_inflight(sock)
        try:
            sock.settimeout(_POLL_S)

            def poll() -> None:
                now = time.monotonic()
                if rep.state in ("lost", "stopped") or rep.generation != generation:
                    raise _DeadSocket(
                        f"replica {rep.index} declared lost mid-query"
                    )
                if deadline_t is not None and now >= deadline_t:
                    raise DeadlineExceeded(
                        f"deadline expired joining replica {rep.index}",
                        where="fleet.join",
                    )
                if now >= watchdog_t:
                    raise DeadlineExceeded(
                        f"global join watchdog expired on replica "
                        f"{rep.index} after {timeout:g}s",
                        deadline_s=timeout,
                        where="fleet.watchdog",
                    )

            try:
                wire.send_msg(sock, msg)
                return wire.recv_msg(sock, poll=poll)
            except wire.WireError as err:
                raise _DeadSocket(str(err)) from err
        finally:
            if track:
                rep.forget_inflight(sock)
            try:
                sock.close()
            except OSError:
                pass

    def submit(
        self,
        dataset: str,
        fn: Any,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        tenant: str = "default",
        deadline_ms: Optional[float] = None,
        label: Optional[str] = None,
        idempotent: bool = True,
    ) -> Any:
        """Route one query; see ``fleet.submit`` for the public contract."""
        start = time.monotonic()
        deadline_t = (
            start + deadline_ms / 1e3
            if deadline_ms is not None and deadline_ms > 0
            else None
        )
        attempts = len(self._replicas) + 1
        for attempt in range(attempts):
            rep = self._route(tenant)
            remaining_ms = deadline_ms
            if deadline_t is not None:
                remaining_s = deadline_t - time.monotonic()
                if remaining_s <= 0:
                    raise DeadlineExceeded(
                        "deadline expired before dispatch",
                        where="fleet.dispatch",
                    )
                remaining_ms = remaining_s * 1e3
            msg = {
                "type": "query",
                "dataset": dataset,
                "fn": fn,
                "args": tuple(args),
                "kwargs": dict(kwargs or {}),
                "tenant": tenant,
                "deadline_ms": remaining_ms,
                "label": label,
            }
            t0 = time.monotonic()
            try:
                reply = self._call(
                    rep, msg, timeout=JOIN_WATCHDOG_S,
                    deadline_t=deadline_t, track=True,
                )
            except _DeadSocket:
                self._declare_lost(rep, "dead_socket")
                if idempotent and attempt + 1 < attempts:
                    emit_metric("fleet.query.redispatch", 1)
                    with self._lock:
                        self.redispatched += 1
                    continue
                raise QueryRejected(
                    f"replica {rep.index} died mid-query and the query is "
                    f"not idempotent-by-lineage",
                    reason="replica_lost",
                    retry_after_s=self._heartbeat_s() * 3,
                )
            wall_s = time.monotonic() - t0
            rep.latencies.append(wall_s)
            with self._lock:
                self.routed += 1
            emit_metric("fleet.query.routed", 1)
            self._observe_replica(rep, wall_s, reply)
            return self._decode(reply)
        raise QueryRejected(  # unreachable backstop: _route raises first
            "no replica completed the query", reason="no_replicas"
        )

    @staticmethod
    def _observe_replica(rep: _Replica, wall_s: float, reply: dict) -> None:
        """Per-replica SLO attribution: the coordinator's watch service
        tracks each replica as a pseudo-tenant (one module-attr check
        when watch is off, the established contract)."""
        from modin_tpu.observability import watch as _watch

        if _watch.WATCH_ON:
            failure = None if reply.get("ok") else reply.get("error")
            _watch.observe_query(f"replica{rep.index}", wall_s, failure)

    @staticmethod
    def _decode(reply: dict) -> Any:
        if reply.get("ok"):
            return reply["result"]
        kind = reply.get("error")
        if kind == "rejected":
            raise QueryRejected(
                reply.get("message", "rejected by replica"),
                reason=reply.get("reason", "queue_full"),
                retry_after_s=reply.get("retry_after_s"),
            )
        if kind == "deadline":
            raise DeadlineExceeded(
                reply.get("message", "deadline exceeded on replica"),
                deadline_s=reply.get("deadline_s", 0.0),
                where=reply.get("where", ""),
            )
        raise QueryRejected(
            f"replica error: {reply.get('message', 'unknown')}",
            reason="replica_error",
        )

    # -- failure detection & recovery ------------------------------------ #

    def _declare_lost(self, rep: _Replica, reason: str) -> None:
        with self._lock:
            if rep.state != "up":
                return  # another observer already handled it
            rep.state = "lost"
            self.lost_count += 1
        # SIGKILL outside the lock: a SIGSTOPed replica must never SIGCONT
        # back to life and serve stale state (SIGKILL applies to stopped
        # processes too)
        if rep.pid is not None:
            try:
                os.kill(rep.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
        emit_metric("fleet.replica.lost", 1)
        rep.interrupt_inflight()
        self._redistribute(rep.index)

    def _redistribute(self, dead_index: int) -> None:
        """Drain the dead replica's tenants onto survivors, weighted-fair
        with each survivor's typed-shed rate as the backpressure signal:
        a shedding survivor absorbs fewer drained tenants."""
        moved = 0
        with self._lock:
            drained = sorted(
                t for t, i in self._assignments.items() if i == dead_index
            )
            survivors = [r for r in self._replicas if r.state == "up"]
            if not survivors:
                for tenant in drained:
                    self._assignments.pop(tenant, None)
                return
            loads: Dict[int, int] = {}
            for t, i in self._assignments.items():
                if i != dead_index:
                    loads[i] = loads.get(i, 0) + 1
            for tenant in drained:
                target = min(
                    survivors,
                    key=lambda r: (
                        (loads.get(r.index, 0) + 1) * (1.0 + r.shed_rate),
                        r.index,
                    ),
                )
                self._assignments[tenant] = target.index
                loads[target.index] = loads.get(target.index, 0) + 1
                moved += 1
            self.redistributed_count += moved
        if moved:
            emit_metric("fleet.drain.redistributed", moved)

    def _probe(self, rep: _Replica) -> bool:
        """Fresh-dial liveness probe: can the replica still answer a ping?
        (A SIGSTOPed process accepts the connect — the kernel's backlog
        does — but never answers; that is exactly the wedge this catches.)"""
        timeout = max(self._heartbeat_s() * 2, 1.0)
        try:
            reply = self._call(rep, {"type": "ping"}, timeout=timeout)
            return bool(reply.get("ok"))
        except (_DeadSocket, DeadlineExceeded):
            return False

    def _monitor_loop(self) -> None:
        graftscope.seed_thread(self._obs_span_stack)
        graftmeter.seed_thread_scopes(self._obs_scopes)
        try:
            while not self._stop.wait(self._heartbeat_s() / 2):
                hb = self._heartbeat_s()
                with self._lock:
                    reps = list(self._replicas)
                for rep in reps:
                    if self._stop.is_set():
                        return
                    if rep.state == "up":
                        if (
                            rep.proc is not None
                            and rep.proc.poll() is not None
                        ):
                            self._declare_lost(rep, "exit")
                        elif time.monotonic() - rep.last_heartbeat > 3 * hb:
                            emit_metric("fleet.replica.heartbeat_miss", 1)
                            if not self._probe(rep):
                                self._declare_lost(rep, "heartbeat")
                    elif rep.state == "lost" and self._respawn_enabled():
                        self._respawn(rep)
        finally:
            graftmeter.seed_thread_scopes(None)
            graftscope.seed_thread(None)

    @staticmethod
    def _respawn_enabled() -> bool:
        from modin_tpu.config import FleetRespawn

        return bool(FleetRespawn.get())

    def _export_views_from_survivor(self) -> Dict[str, List[dict]]:
        """A healthy survivor's graftview artifact export (best-effort:
        warm answers are an optimization, never a respawn blocker)."""
        for rep in self._up_replicas():
            try:
                reply = self._call(
                    rep, {"type": "export_views"}, timeout=JOIN_WATCHDOG_S
                )
                if reply.get("ok"):
                    return reply.get("views", {})
            except (_DeadSocket, DeadlineExceeded):
                continue
        return {}

    def _respawn(self, rep: _Replica) -> None:
        """Fresh generation: spawn, hello, warm (manifest + survivor's
        artifacts), then route to it again.  Any failure returns the slot
        to ``lost`` and the next monitor tick retries."""
        from modin_tpu.core.execution import recovery

        with self._lock:
            if rep.state != "lost":
                return
            rep.state = "respawning"
            rep.generation += 1
            rep.shed_rate = 0.0
            rep.latencies.clear()
        if rep.proc is not None:
            try:
                rep.proc.wait(timeout=10)
            except Exception:
                pass
        try:
            self._spawn(rep)
            if not rep.hello_event.wait(_HELLO_TIMEOUT_S):
                raise _DeadSocket(
                    f"respawned replica {rep.index} never said hello"
                )
            views = self._export_views_from_survivor()
            reply = self._call(
                rep,
                {
                    "type": "warm",
                    "manifest": recovery.dataset_manifest(),
                    "views": views,
                },
                timeout=JOIN_WATCHDOG_S,
            )
            if not reply.get("ok"):
                raise _DeadSocket(
                    f"respawned replica {rep.index} failed to warm: "
                    f"{reply.get('message')}"
                )
        except (_DeadSocket, DeadlineExceeded, OSError):
            with self._lock:
                rep.state = "lost"
                self.respawn_failures += 1
            if rep.pid is not None:
                try:
                    os.kill(rep.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
            return
        with self._lock:
            rep.state = "up"
            rep.last_heartbeat = time.monotonic()
            self.respawned_count += 1
        emit_metric("fleet.replica.respawned", 1)

    # -- introspection ---------------------------------------------------- #

    def snapshot(self) -> dict:
        """The replica table + routing counters (serving_snapshot and the
        /statusz fleet section render exactly this)."""
        with self._lock:
            rows = []
            for rep in self._replicas:
                lat = list(rep.latencies)
                p50 = _percentile(lat, 0.50)
                p99 = _percentile(lat, 0.99)
                rows.append(
                    {
                        "index": rep.index,
                        "state": rep.state,
                        "generation": rep.generation,
                        "pid": rep.pid,
                        "rpc_port": rep.rpc_port,
                        "watch_port": rep.watch_port,
                        "tenants": sum(
                            1
                            for i in self._assignments.values()
                            if i == rep.index
                        ),
                        "in_flight": len(rep.inflight_socks),
                        "shed_rate": rep.shed_rate,
                        "heartbeat_age_s": (
                            round(time.monotonic() - rep.last_heartbeat, 3)
                            if rep.last_heartbeat
                            else None
                        ),
                        "p50_ms": None if p50 is None else p50 * 1e3,
                        "p99_ms": None if p99 is None else p99 * 1e3,
                        "counters": dict(rep.heartbeat_counters),
                    }
                )
            return {
                "replicas": rows,
                "assignments": dict(self._assignments),
                "routed": self.routed,
                "redispatched": self.redispatched,
                "lost": self.lost_count,
                "respawned": self.respawned_count,
                "redistributed": self.redistributed_count,
                "respawn_failures": self.respawn_failures,
                "control_port": self._control_port,
            }
