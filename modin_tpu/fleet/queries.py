"""Stock fleet query operations, resolvable by name in every process.

A routed query crosses the coordinator->replica socket, so its callable
must be importable on the far side — a lambda or ``__main__``-local
function pickled by reference resolves against the *replica's* main
module and fails.  ``fleet.submit`` therefore accepts either a name from
this catalog (always safe) or a module-qualified picklable callable.

Every op takes the dataset frame first and returns a HOST (pandas)
result: answers must pickle across the socket, and the local
(fleet-off) path returns the identical object shape so the two modes
compare bit-for-bit.
"""

from __future__ import annotations

from typing import Any, Callable, Dict


def _host(result: Any) -> Any:
    return result._to_pandas() if hasattr(result, "_to_pandas") else result


def q_sum(frame: Any) -> Any:
    return _host(frame.sum())


def q_mean(frame: Any) -> Any:
    return _host(frame.mean())


def q_count(frame: Any) -> Any:
    return _host(frame.count())


def q_min(frame: Any) -> Any:
    return _host(frame.min())


def q_max(frame: Any) -> Any:
    return _host(frame.max())


def q_groupby_sum(frame: Any, key: str = "k") -> Any:
    return _host(frame.groupby(key).sum())


def q_filter_sum(frame: Any, column: str = "i", threshold: float = 0) -> Any:
    return _host(frame[frame[column] > threshold].sum())


QUERIES: Dict[str, Callable] = {
    "sum": q_sum,
    "mean": q_mean,
    "count": q_count,
    "min": q_min,
    "max": q_max,
    "groupby_sum": q_groupby_sum,
    "filter_sum": q_filter_sum,
}


def resolve(query: Any) -> Callable:
    """The callable for ``query`` (a catalog name or a callable)."""
    if callable(query):
        return query
    fn = QUERIES.get(query)
    if fn is None:
        raise KeyError(
            f"unknown fleet query {query!r}; catalog: {sorted(QUERIES)}"
        )
    return fn
