"""graftfleet wire protocol: length-prefixed pickle frames over local sockets.

One frame = a 4-byte big-endian length prefix + a pickled payload.  Both
sides of every fleet socket (coordinator control listener, replica RPC
listener, heartbeat stream) speak exactly this; there is no partial-frame
state machine beyond "read until the frame is whole".

Two properties matter for the failure-detection contract:

- **Bounded frames.**  A frame longer than ``MAX_FRAME_BYTES`` is a
  protocol error, not an allocation — a corrupted or adversarial length
  prefix cannot make a reader allocate gigabytes.
- **Interruptible reads.**  ``recv_msg`` accepts a ``poll`` callback
  invoked on every socket-timeout tick while a frame is incomplete; the
  coordinator's dispatch path uses it to abort a blocked join the moment
  the monitor declares the replica lost (the SIGSTOP-hang case: the
  socket stays connected but no bytes ever arrive).
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Any, Callable, Optional

#: Hard cap on one frame's payload (a full exported dataset result fits
#: comfortably; a garbage length prefix does not).
MAX_FRAME_BYTES = 256 * 1024 * 1024

_LEN = struct.Struct(">I")


class WireError(ConnectionError):
    """The peer vanished or spoke garbage mid-frame (dead-socket signal)."""


def send_msg(sock: socket.socket, obj: Any) -> None:
    """Pickle ``obj`` and send it as one frame (raises WireError on a dead
    peer)."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > MAX_FRAME_BYTES:
        raise WireError(f"frame of {len(payload)} bytes exceeds the cap")
    try:
        sock.sendall(_LEN.pack(len(payload)) + payload)
    except (OSError, ValueError) as err:
        raise WireError(f"send failed: {err}") from err


def _recv_exact(
    sock: socket.socket, n: int, poll: Optional[Callable[[], None]]
) -> bytes:
    """Read exactly ``n`` bytes, calling ``poll()`` on every timeout tick.

    ``poll`` aborts the read by raising; returning lets the read continue
    waiting.  A peer that closes (or resets) mid-frame raises WireError.
    """
    chunks = []
    remaining = n
    while remaining > 0:
        try:
            chunk = sock.recv(min(remaining, 1 << 20))
        except socket.timeout:
            if poll is not None:
                poll()
            continue
        except OSError as err:
            raise WireError(f"recv failed: {err}") from err
        if not chunk:
            raise WireError("peer closed mid-frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def recv_msg(
    sock: socket.socket, poll: Optional[Callable[[], None]] = None
) -> Any:
    """Receive one frame and unpickle it.

    The caller controls responsiveness via the socket's timeout: each
    timeout tick invokes ``poll()`` (see module docstring) and the read
    resumes, so a frame split across ticks is never lost.
    """
    header = _recv_exact(sock, _LEN.size, poll)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME_BYTES:
        raise WireError(f"peer announced a {length}-byte frame (cap exceeded)")
    payload = _recv_exact(sock, length, poll)
    try:
        return pickle.loads(payload)
    except Exception as err:
        raise WireError(f"frame did not unpickle: {err}") from err


def connect(
    host: str, port: int, timeout: Optional[float] = None
) -> socket.socket:
    """A connected TCP socket with TCP_NODELAY (frames are small and the
    RPC is latency-sensitive)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    return sock
