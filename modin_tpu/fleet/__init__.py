"""graftfleet — replicated serving fleet (ISSUE 16).

A coordinator (:mod:`modin_tpu.fleet.coordinator`) supervises N replica
serving processes (:mod:`modin_tpu.fleet.replica`), each with its own
virtual device mesh, admission gate, and watch exporter on an ephemeral
port.  Tenant queries route over local socket RPC with deadline
propagation; replica failure is detected three independent ways
(heartbeat loss, liveness-probe timeout, dead socket on dispatch); lost
replicas drain their tenants onto survivors weighted by typed-shed-rate
backpressure, respawn a fresh generation, and re-warm from the dataset
manifest (re-read through the public readers, so io lineage / spans /
cost accounting all see the replay) plus a survivor's exported graftview
artifacts.

``MODIN_TPU_FLEET=0`` (the default) is the whole story for everyone
else: no coordinator, no sockets, no threads — ``submit`` is one module
attribute check and then the exact local serving path, and
``fleet_alloc_count()`` stays 0 (the graftscope zero-overhead-when-off
contract, asserted in tests).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

#: fast-path flag: True while MODIN_TPU_FLEET resolves truthy.  Every
#: fleet hook on a hot path reads this one attribute and nothing else.
FLEET_ON: bool = False

#: fleet-object allocation counter (Coordinator + replica records); the
#: off-mode zero-overhead assertion reads this through
#: :func:`fleet_alloc_count`.
_alloc_count: int = 0

#: the process's coordinator (exactly one per fleet-enabled process)
_coordinator: Optional[Any] = None

#: fleet-off working set: dataset name -> locally-warmed frame, so the
#: two modes answer the same ``submit`` calls bit-for-bit
_local_frames: Dict[str, Any] = {}


def _note_alloc() -> None:
    global _alloc_count
    _alloc_count += 1


def fleet_alloc_count() -> int:
    """How many fleet objects this process ever allocated (0 when the
    fleet never started — the zero-overhead-when-off assertion)."""
    return _alloc_count


def get_coordinator() -> Optional[Any]:
    """The live coordinator, or None (fleet off / never started /
    replica process)."""
    return _coordinator


def start_fleet(replicas: Optional[int] = None) -> Any:
    """Spawn and supervise the replica fleet; idempotent per process.

    Requires ``MODIN_TPU_FLEET=1``; replica count defaults to
    ``MODIN_TPU_FLEET_REPLICAS``.  Blocks until every replica has said
    hello (imported the serving substrate and bound its ports).
    """
    global _coordinator
    if not FLEET_ON:
        raise RuntimeError(
            "MODIN_TPU_FLEET is off; enable it (or FleetEnabled.enable()) "
            "before start_fleet()"
        )
    if _coordinator is not None:
        return _coordinator
    from modin_tpu.fleet.coordinator import Coordinator

    coord = Coordinator(replicas)
    try:
        coord.start()
    except Exception:
        coord.stop()
        raise
    _coordinator = coord
    return coord


def stop_fleet() -> None:
    """Tear the fleet down (kill replicas, close sockets); idempotent."""
    global _coordinator
    coord = _coordinator
    _coordinator = None
    if coord is not None:
        coord.stop()


def register_dataset(name: str, reader: str, *args: Any, **kwargs: Any) -> None:
    """Register a serving dataset: ``reader`` (a ``modin_tpu.pandas``
    reader name, e.g. ``"read_csv"``) applied to ``args``/``kwargs``.

    The entry lands in the recovery manifest either way — that is what a
    respawned replica re-warms from.  Fleet on: every live replica warms
    it now.  Fleet off: it is read locally, through the same public
    reader path a replica would use.
    """
    if FLEET_ON and _coordinator is not None:
        _coordinator.register_dataset(name, reader, tuple(args), dict(kwargs))
        return
    from modin_tpu.core.execution import recovery

    recovery.register_dataset(name, reader, tuple(args), dict(kwargs))
    import modin_tpu.pandas as _mpd

    fn = getattr(_mpd, reader, None)
    if fn is None or not callable(fn):
        raise ValueError(f"unknown modin_tpu.pandas reader {reader!r}")
    _local_frames[str(name)] = fn(*args, **kwargs)


def submit(
    dataset: str,
    query: Any,
    *args: Any,
    tenant: str = "default",
    deadline_ms: Optional[float] = None,
    label: Optional[str] = None,
    idempotent: bool = True,
    **kwargs: Any,
) -> Any:
    """Run one query against a registered dataset, fleet-routed when on.

    ``query`` is a catalog name from :mod:`modin_tpu.fleet.queries` or a
    module-qualified picklable callable ``fn(frame, *args, **kwargs)``.
    The outcome is always typed: the (host) result, ``QueryRejected``, or
    ``DeadlineExceeded`` — never a hang (deadline propagation + the
    coordinator's global join watchdog) and never an untyped error.

    ``idempotent`` declares the query safe to re-dispatch to a survivor
    if its replica dies mid-flight (true for everything lineage-replayable
    from the manifest, which is every catalog op); non-idempotent queries
    surface ``QueryRejected(reason="replica_lost")`` instead.
    """
    from modin_tpu.fleet import queries as _queries

    fn = _queries.resolve(query)
    if label is None and isinstance(query, str):
        label = query
    if FLEET_ON and _coordinator is not None:
        return _coordinator.submit(
            str(dataset),
            fn,
            args=tuple(args),
            kwargs=dict(kwargs),
            tenant=tenant,
            deadline_ms=deadline_ms,
            label=label,
            idempotent=idempotent,
        )
    frame = _local_frames.get(str(dataset))
    if frame is None:
        from modin_tpu.serving.errors import QueryRejected

        raise QueryRejected(
            f"no dataset {dataset!r} registered", reason="unknown_dataset"
        )
    from modin_tpu.serving import gate as _gate

    return _gate.submit(
        fn,
        frame,
        *args,
        tenant=tenant,
        deadline_ms=deadline_ms,
        label=label,
        **kwargs,
    )


def fleet_snapshot() -> dict:
    """Introspection: enabled flag + the coordinator's replica table
    (empty when no coordinator lives in this process)."""
    snap = {
        "enabled": FLEET_ON,
        "active": _coordinator is not None,
        "alloc_count": _alloc_count,
        "local_datasets": sorted(_local_frames),
    }
    if _coordinator is not None:
        snap.update(_coordinator.snapshot())
    return snap


def reset_for_tests() -> None:
    """Tear down any fleet and clear the local working set (alloc counter
    intentionally survives: it counts a process's lifetime allocations)."""
    stop_fleet()
    _local_frames.clear()


def _on_fleet_enabled(param: Any) -> None:
    global FLEET_ON
    FLEET_ON = bool(param.get())


from modin_tpu.config import FleetEnabled as _FleetEnabled  # noqa: E402

_FleetEnabled.subscribe(_on_fleet_enabled)
