"""graftfleet replica: one supervised serving process.

Runnable as ``python -m modin_tpu.fleet.replica``; only the coordinator
spawns it.  The contract with the coordinator, all over the wire protocol
(fleet/wire.py):

1. **Hello.**  Connect the control socket to
   ``MODIN_TPU_FLEET_COORD`` and announce ``{index, generation, pid,
   rpc_port, watch_port}``.  The RPC port is bound ephemeral here; the
   watch exporter's port was forced ephemeral by the coordinator
   (``MODIN_TPU_WATCH_PORT=0`` in the spawn env) and the *bound* port is
   read back live — two replicas on one host can never collide on a
   user-pinned fixed port.
2. **Heartbeats.**  A daemon thread sends ``{shed_rate, gate counters}``
   every ``MODIN_TPU_FLEET_HEARTBEAT_S`` on the control socket.  The
   shed rate is the admission gate's windowed typed-shed rate — the
   backpressure signal the coordinator weighs redistribution by.  A dead
   control socket means the coordinator is gone: the replica exits
   rather than serve unsupervised.
3. **RPC.**  Connection-per-request on the ephemeral RPC listener:
   ``ping`` (liveness probe), ``warm`` (dataset-manifest replay through
   the public readers + graftview artifact ingest), ``query`` (run one
   catalog/pickled query through the local ``serving.submit`` with the
   coordinator's remaining deadline), ``export_views`` (artifact export
   for warming a respawned peer), ``snapshot``, ``shutdown``.

Every query outcome crossing the wire is typed: a result payload, a
serialized ``QueryRejected``/``DeadlineExceeded``, or — for an escaped
untyped error, itself a contract violation — an ``internal`` record the
coordinator surfaces as a typed rejection.  The replica never answers a
query with silence; silence is what the coordinator's failure detection
is for.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Any, Dict, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.fleet import wire

#: dataset name -> warmed frame (this process's serving working set)
_frames: Dict[str, Any] = {}
_frames_lock = named_lock("fleet.frames")

#: serialized control-socket writes (hello/heartbeat share one socket)
_control_lock = named_lock("fleet.control")
_control_sock: Optional[socket.socket] = None

#: spawn-time observability context for this process's service threads
#: (snapshotted in main() once the serving substrate is imported)
_obs_span_stack: Any = None
_obs_scopes: Any = None


def _watch_port() -> int:
    """The watch exporter's live bound port (-1 when not serving)."""
    try:
        from modin_tpu.observability import watch

        port = watch.httpd_port()
        return int(port) if port is not None else -1
    except Exception:
        return -1


def _heartbeat_loop(index: int, generation: int) -> None:
    import time

    from modin_tpu.config import FleetHeartbeatS
    from modin_tpu.observability import meters as graftmeter
    from modin_tpu.observability import spans as graftscope
    from modin_tpu.serving.gate import gate

    graftscope.seed_thread(_obs_span_stack)
    graftmeter.seed_thread_scopes(_obs_scopes)
    while True:
        time.sleep(float(FleetHeartbeatS.get()))
        snap = gate.snapshot()
        beat = {
            "type": "heartbeat",
            "index": index,
            "generation": generation,
            "shed_rate": snap["shed_rate"],
            "running": snap["running"],
            "shed": snap["shed"],
            "admitted": snap["admitted"],
            "completed": snap["completed"],
            "watch_port": _watch_port(),
        }
        try:
            with _control_lock:
                # graftlint: disable=LOCK-BLOCKING -- fleet.control's entire purpose is serializing this one socket's frame writes; interleaved sends would corrupt the wire protocol
                wire.send_msg(_control_sock, beat)
        except wire.WireError:
            os._exit(0)  # coordinator gone: never serve unsupervised


def _run_query(req: dict) -> dict:
    from modin_tpu.serving import gate as gate_mod
    from modin_tpu.serving.errors import DeadlineExceeded, QueryRejected

    with _frames_lock:
        frame = _frames.get(req["dataset"])
    if frame is None:
        return {
            "ok": False,
            "error": "rejected",
            "message": f"replica has no dataset {req['dataset']!r}",
            "reason": "unknown_dataset",
            "retry_after_s": None,
        }
    try:
        result = gate_mod.submit(
            req["fn"],
            frame,
            *req.get("args", ()),
            tenant=req.get("tenant", "default"),
            deadline_ms=req.get("deadline_ms"),
            label=req.get("label"),
            **req.get("kwargs", {}),
        )
        return {"ok": True, "result": result}
    except QueryRejected as err:
        return {
            "ok": False,
            "error": "rejected",
            "message": str(err),
            "reason": err.reason,
            "retry_after_s": err.retry_after_s,
        }
    except DeadlineExceeded as err:
        return {
            "ok": False,
            "error": "deadline",
            "message": str(err),
            "deadline_s": err.deadline_s,
            "where": err.where,
        }
    except Exception as err:
        # an untyped error is a contract bug, but the wire answer must
        # still be typed, never silence
        return {
            "ok": False,
            "error": "internal",
            "message": f"{type(err).__name__}: {err}"[:500],
        }


def _handle_request(req: dict) -> dict:
    kind = req.get("type")
    if kind == "ping":
        return {"ok": True, "pid": os.getpid(), "datasets": sorted(_frames)}
    if kind == "warm":
        if os.environ.get("MODIN_TPU_FLEET_TEST_CRASH") == "warm":
            os._exit(3)  # ReplicaFaultInjector crash-during-respawn leg
        from modin_tpu.core.execution import recovery
        from modin_tpu.views import exporter as view_exporter

        frames = recovery.warm_from_manifest(req.get("manifest", []))
        with _frames_lock:
            _frames.update(frames)
        ingested = view_exporter.ingest_datasets(
            _frames, req.get("views") or {}
        )
        feeds_recovered = 0
        dur_dir = os.environ.get("MODIN_TPU_FLEET_DURABILITY_DIR")
        if dur_dir:
            # graftwal: a respawned replica comes back with its durable
            # feeds and live views intact (checkpoint + WAL-tail replay),
            # not just whatever the manifest/exporter captured
            from modin_tpu import durability

            feeds_recovered = durability.recover_feeds(dur_dir)
        return {
            "ok": True,
            "datasets": sorted(_frames),
            "views_ingested": ingested,
            "feeds_recovered": feeds_recovered,
        }
    if kind == "query":
        return _run_query(req)
    if kind == "export_views":
        from modin_tpu.views import exporter as view_exporter

        with _frames_lock:
            frames = dict(_frames)
        return {"ok": True, "views": view_exporter.export_datasets(frames)}
    if kind == "snapshot":
        from modin_tpu.serving.gate import serving_snapshot

        snap = {"ok": True, "serving": serving_snapshot()}
        try:
            from modin_tpu.observability import meters

            snap["meters"] = meters.snapshot()
        except Exception:
            pass
        return snap
    if kind == "shutdown":
        return {"ok": True, "bye": True}
    return {"ok": False, "error": "internal", "message": f"unknown rpc {kind!r}"}


def _serve_connection(conn: socket.socket) -> None:
    from modin_tpu.observability import meters as graftmeter
    from modin_tpu.observability import spans as graftscope

    graftscope.seed_thread(_obs_span_stack)
    graftmeter.seed_thread_scopes(_obs_scopes)
    try:
        conn.settimeout(30.0)
        req = wire.recv_msg(conn)
        conn.settimeout(None)
        reply = _handle_request(req)
        wire.send_msg(conn, reply)
        if reply.get("bye"):
            conn.close()
            os._exit(0)
    except wire.WireError:
        pass  # the peer (or its query) died; nothing to answer
    finally:
        try:
            conn.close()
        except OSError:
            pass
        graftmeter.seed_thread_scopes(None)
        graftscope.seed_thread(None)


def main() -> int:
    global _control_sock, _obs_span_stack, _obs_scopes

    coord = os.environ["MODIN_TPU_FLEET_COORD"]
    index = int(os.environ["MODIN_TPU_FLEET_INDEX"])
    generation = int(os.environ.get("MODIN_TPU_FLEET_GEN", "0"))
    host, _, port_text = coord.rpartition(":")

    # Build the serving substrate BEFORE hello: "hello" means "ready".
    import modin_tpu.pandas  # noqa: F401

    from modin_tpu.observability import meters as graftmeter
    from modin_tpu.observability import spans as graftscope

    _obs_span_stack = graftscope.snapshot_stack()
    _obs_scopes = graftmeter.snapshot_scopes()

    rpc = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    rpc.bind(("127.0.0.1", 0))
    rpc.listen(64)
    rpc_port = rpc.getsockname()[1]

    _control_sock = wire.connect(host, int(port_text), timeout=10.0)
    _control_sock.settimeout(None)
    with _control_lock:
        # graftlint: disable=LOCK-BLOCKING -- fleet.control's entire purpose is serializing this one socket's frame writes; interleaved sends would corrupt the wire protocol
        wire.send_msg(
            _control_sock,
            {
                "type": "hello",
                "index": index,
                "generation": generation,
                "pid": os.getpid(),
                "rpc_port": rpc_port,
                "watch_port": _watch_port(),
            },
        )
    threading.Thread(
        target=_heartbeat_loop,
        args=(index, generation),
        name=f"modin-tpu-fleet-heartbeat-{index}",
        daemon=True,
    ).start()

    while True:
        try:
            conn, _addr = rpc.accept()
        except OSError:
            return 0
        threading.Thread(
            target=_serve_connection,
            args=(conn,),
            name=f"modin-tpu-fleet-rpc-{index}",
            daemon=True,
        ).start()


if __name__ == "__main__":
    raise SystemExit(main())
