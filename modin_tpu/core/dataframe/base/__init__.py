"""modin_tpu subpackage."""
