"""Abstract core-frame contract.

Reference: modin/core/dataframe/base/dataframe/dataframe.py:26
(``ModinDataframe``) pins the dataframe-algebra surface every core frame
must expose, independent of the partitioning substrate.  The tpu
translation keeps the same role — one pluggable seam below the query
compiler — but the algebra is adapted to the columnar sharded store:

- the reference's 2-D block grid operators (``map``/``fold``/``reduce``
  over partitions) do not appear here because fan-out IS compilation in
  this design: one jitted kernel over whole device columns replaces a
  partition sweep, so compute enters through the ``ops/`` kernel modules,
  not through a frame method taking a Python callable;
- what remains frame-shaped is the STRUCTURAL algebra — selection,
  projection, masking, concatenation, relabeling — plus the host/device
  materialization lifecycle, and that is the contract below.

``TpuDataframe`` is the device implementation.  A hypothetical second
storage format (e.g. an Arrow-backed host frame) would implement this same
surface and slot under the existing query compilers unchanged.
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional, Sequence

import pandas


class BaseDataframe(abc.ABC):
    """The structural dataframe algebra + materialization lifecycle."""

    # ---------------------------- construction ------------------------ #

    @classmethod
    @abc.abstractmethod
    def from_pandas(cls, df: pandas.DataFrame) -> "BaseDataframe":
        """Build a frame from host pandas data."""

    @abc.abstractmethod
    def to_pandas(self) -> pandas.DataFrame:
        """Materialize the full frame on the host, bit-exact."""

    @abc.abstractmethod
    def to_numpy(self, **kwargs: Any) -> Any:
        """Materialize the frame as a single 2-D ndarray."""

    # ------------------------------ axes ------------------------------ #

    @property
    @abc.abstractmethod
    def index(self) -> pandas.Index:
        """Row labels (may force a lazily deferred index)."""

    @property
    @abc.abstractmethod
    def columns(self) -> pandas.Index:
        """Column labels."""

    @property
    @abc.abstractmethod
    def dtypes(self) -> pandas.Series:
        """Per-column pandas dtypes."""

    @abc.abstractmethod
    def __len__(self) -> int:
        """Number of rows (never forces the index)."""

    # ----------------------- structural algebra ----------------------- #
    # selection/projection/masking/concat: the reference's
    # take_2d_labels_or_positional + filter + concat rows
    # (modin/core/dataframe/base/dataframe/dataframe.py:38,:278,:499),
    # split into orthogonal primitives so lazy metadata survives each.

    @abc.abstractmethod
    def select_columns_by_position(
        self, positions: Sequence[int]
    ) -> "BaseDataframe":
        """Projection: keep the columns at ``positions`` (order honored)."""

    @abc.abstractmethod
    def rename_columns(self, new_labels: pandas.Index) -> "BaseDataframe":
        """Relabel columns without touching data."""

    @abc.abstractmethod
    def with_columns(
        self, positions: Sequence[int], new_columns: Sequence[Any]
    ) -> "BaseDataframe":
        """Replace the columns at ``positions`` with ``new_columns``."""

    @abc.abstractmethod
    def take_rows_positional(self, positions: Any) -> "BaseDataframe":
        """Selection: gather rows by position (slice, range, or array)."""

    @abc.abstractmethod
    def filter_rows_mask(self, mask: Any) -> "BaseDataframe":
        """Selection: keep rows where ``mask`` is true."""

    @abc.abstractmethod
    def concat_rows(self, others: List["BaseDataframe"]) -> "BaseDataframe":
        """Stack frames with identical column sets along axis 0."""

    # ----------------------- materialization -------------------------- #

    @abc.abstractmethod
    def copy(self) -> "BaseDataframe":
        """A frame sharing immutable column data (columns are replaced,
        never mutated, so sharing is safe)."""

    @abc.abstractmethod
    def finalize(self) -> None:
        """Force every deferred computation (lazy columns, deferred index)
        so subsequent accesses are pure reads.  The reference's
        ``ModinDataframe.finalize`` (dataframe.py:729)."""

    @abc.abstractmethod
    def free(self) -> None:
        """Release device buffers (spill/teardown hook)."""


def __getattr__(name: str) -> Any:  # pragma: no cover - import convenience
    if name == "TpuDataframe":
        from modin_tpu.core.dataframe.tpu.dataframe import TpuDataframe

        return TpuDataframe
    raise AttributeError(name)


__all__ = ["BaseDataframe"]
