"""Lazy axis metadata for the TPU frame.

Reference design: modin/core/dataframe/pandas/metadata/index.py:24 (ModinIndex:
value-or-callable with caching).  Device computations produce frames whose row
labels are a deferred gather (e.g. after filter/sort); materializing the index
eagerly would force a device sync, so it stays a thunk until someone asks.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import pandas


class LazyIndex:
    """A pandas Index, or a thunk that computes one (cached)."""

    def __init__(self, value: Union[pandas.Index, Callable[[], pandas.Index]], length: Optional[int] = None):
        if callable(value):
            self._value = None
            self._thunk = value
        else:
            self._value = ensure_index(value)
            self._thunk = None
        self._length = length if length is not None else (
            len(self._value) if self._value is not None else None
        )

    @property
    def is_materialized(self) -> bool:
        return self._value is not None

    def get(self) -> pandas.Index:
        if self._value is None:
            self._value = ensure_index(self._thunk())
            self._thunk = None
            if self._length is None:
                self._length = len(self._value)
        return self._value

    def __len__(self) -> int:
        if self._length is None:
            self.get()
        return self._length

    def has_known_length(self) -> bool:
        return self._length is not None

    def copy(self) -> "LazyIndex":
        if self._value is not None:
            return LazyIndex(self._value, self._length)
        return LazyIndex(self._thunk, self._length)

    def map_after(self, fn: Callable[[pandas.Index], pandas.Index], length: Optional[int] = None) -> "LazyIndex":
        """A new LazyIndex applying ``fn`` to this one when materialized."""
        return LazyIndex(lambda: fn(self.get()), length)


def ensure_index(value: Any) -> pandas.Index:
    if isinstance(value, pandas.Index):
        return value
    return pandas.Index(value)
