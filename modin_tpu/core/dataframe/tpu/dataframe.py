"""``TpuDataframe`` — the sharded columnar core frame.

TPU-native re-design of the reference's core frame
(modin/core/dataframe/pandas/dataframe/dataframe.py:82).  Instead of a 2-D
grid of pandas-block partitions on worker processes, a frame is:

- host metadata: column labels (pandas.Index), a lazy row index (LazyIndex),
  per-column logical dtypes;
- per column, either a **DeviceColumn** (1-D jax.Array sharded over the mesh
  "rows" axis — row-partitioning is the sharding spec, SURVEY.md §7) or a
  **HostColumn** (numpy/extension array for object/string dtypes — the
  device/host split that replaces the reference's default-to-pandas partition
  fallback).

Device columns are **padded** to a multiple of the mesh row-shard count with
the logical length tracked per column: XLA requires even shards for
explicitly sharded arrays, and uneven results silently fall back to
replication.  All device kernels (modin_tpu/ops/) are pad-aware.

Datetimes/timedeltas live on device as int64 with a logical-dtype tag; NaT is
the int64 min sentinel, exactly pandas' own representation, so the round-trip
is a zero-cost view.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np
import pandas

from pandas.api.types import is_object_dtype as _is_object_dtype

from modin_tpu.core.dataframe.base.dataframe import BaseDataframe
from modin_tpu.core.dataframe.tpu.metadata import LazyIndex, ensure_index
from modin_tpu.logging import ClassLogger

_DEVICE_NUMPY_KINDS = "biuf"  # bool, int, uint, float


def _is_device_dtype(dtype: Any) -> bool:
    """Whether a pandas dtype can live on device."""
    if not isinstance(dtype, np.dtype):
        return False
    if dtype.kind in _DEVICE_NUMPY_KINDS and dtype.itemsize <= 8:
        return True
    # naive datetime64/timedelta64 (any unit) as int64 + logical tag; the NaT
    # sentinel (int64 min) is unit-independent
    return dtype.kind in "mM" and dtype.itemsize == 8


def _device_layout_values(values: np.ndarray) -> np.ndarray:
    """The dtype-policy transform of host values for device residence
    (datetime int64 view, Downcast float32 policy, contiguity).  The ONE
    transform shared by full uploads (``_device_put_values``) and the
    graftmesh single-shard re-seat, so a recovered shard's slice is always
    byte-identical to what a full upload would have put there.
    """
    from modin_tpu.config import Float64Policy

    device_values = values.view("int64") if values.dtype.kind in "mM" else values
    if device_values.dtype == np.float64 and Float64Policy.get() == "Downcast":
        # f64 on TPU is double-float emulated (~2x the FLOPs, half the
        # VPU/MXU rate); the Downcast policy stores f32 on device while
        # the logical dtype and host_cache keep exact float64 — the user
        # opts into f32 compute precision for device kernels.
        device_values = device_values.astype(np.float32)
    if not device_values.flags.c_contiguous:
        device_values = np.ascontiguousarray(device_values)
    return device_values


def _device_put_values(values: np.ndarray, sharding: Any = None) -> Any:
    """Host values -> padded device buffer under the dtype policy.

    The transform ``from_numpy`` applies (datetime int64 view, Downcast
    float32 policy, contiguity, shard padding), shared with the graftguard
    spill-restore and lineage re-seat paths so a recovered buffer is
    byte-identical to the original upload.
    """
    from modin_tpu.ops.structural import pad_host
    from modin_tpu.parallel.engine import JaxWrapper

    return JaxWrapper.put(pad_host(_device_layout_values(values)), sharding)


class DeviceColumn:
    """One column as a padded 1-D jax.Array sharded over the mesh rows axis.

    ``length`` is the logical row count (data.shape[0] is padded up to a
    multiple of the shard count; pad rows are never read).

    ``host_cache`` keeps the original (unpadded) host numpy array for columns
    that came from the host unchanged: it makes device round-trips bit-exact
    even where the accelerator emulates the dtype (TPU f64 is double-float:
    ~2^-49 relative precision with a float32 exponent range) and lets the
    default-to-pandas path skip the device->host transfer entirely.  Any
    computed column drops the cache.

    graftguard state (core/execution/recovery.py, core/memory.py):
    ``lineage`` is the creation-time provenance record; ``_device_epoch``
    stamps which device incarnation the buffer belongs to; ``_dev_key``
    is the device-memory ledger handle.  A **spilled** column has
    ``_data is None`` and an exact ``host_cache`` — the buffer restores
    transparently on the next ``raw``/``data`` access.
    """

    __slots__ = (
        "_data", "pandas_dtype", "length", "host_cache", "_ledger_key",
        "lineage", "_device_epoch", "_dev_key", "_sorted_rep", "donated",
        "_view_token", "_view_parent",
        "__weakref__",
    )
    is_device = True

    def __init__(
        self,
        data: Any,
        pandas_dtype: np.dtype,
        length: Optional[int] = None,
        host_cache: Optional[np.ndarray] = None,
    ):
        # data: concrete jax.Array OR a deferred LazyExpr (ops/lazy.py);
        # lazy columns materialize on .data access — fusion-aware consumers
        # read .raw instead to keep chains deferred.
        self._data = data
        self.pandas_dtype = np.dtype(pandas_dtype)
        self.length = int(length) if length is not None else int(data.shape[0])
        self.host_cache = host_cache
        self._ledger_key = None
        self.lineage = None
        self._device_epoch = 0
        self._dev_key = None
        self._sorted_rep = None  # graftsort: cached (sorted, n_valid) rep
        self.donated = False  # graftfuse: buffer consumed by a donated dispatch
        # graftview identity: process-unique token (lazily allocated) and
        # the (parent_token, parent_length) append link
        self._view_token = None
        self._view_parent = None
        if host_cache is not None:
            # host caches count against the Memory spill budget (core/memory.py)
            from modin_tpu.core.memory import ledger

            ledger.register(self)
        from modin_tpu.ops.lazy import LazyExpr

        if data is not None and not isinstance(data, LazyExpr):
            # a LazyExpr (even a memoized one) registers on materialization;
            # only a concrete device buffer belongs in the ledgers
            self._register_device()
            from modin_tpu.core.execution import recovery

            recovery.attach_lineage(self)

    @property
    def data(self) -> Any:
        from modin_tpu.ops.lazy import LazyExpr, materialize

        if self._data is None:
            self._restore()
        if isinstance(self._data, LazyExpr):
            self._data = materialize(self._data)
            self._on_materialized()
        return self._data

    @property
    def raw(self) -> Any:
        """The underlying array or deferred expression, unmaterialized
        (a spilled column transparently restores its device buffer)."""
        if self._data is None:
            self._restore()
        return self._data

    @property
    def is_lazy(self) -> bool:
        from modin_tpu.ops.lazy import is_lazy

        return is_lazy(self._data)

    @property
    def is_spilled(self) -> bool:
        """Device buffer dropped; host_cache is the (exact) only copy."""
        return self._data is None

    # -- graftguard: ledger registration, spill/restore, re-seat -------- #

    def _register_device(self) -> None:
        """Track the concrete buffer in the device-memory ledger and stamp
        the current device epoch (recovery provenance indexing rides on
        the same registration)."""
        from modin_tpu.core.execution import recovery
        from modin_tpu.core.memory import device_ledger

        device_ledger.register(self)
        self._device_epoch = recovery.current_epoch()
        self.donated = False  # a fresh buffer: the donation is history
        recovery.note_column_data(self)

    def _on_materialized(self) -> None:
        """A deferred expression just became a concrete device buffer."""
        from modin_tpu.core.execution import recovery

        self._invalidate_sorted()
        self._register_device()
        recovery.attach_lineage(self)

    def _invalidate_sorted(self) -> None:
        """Drop every derived cache answering for this column's buffer —
        it is about to change (spill / re-seat / materialize / donation):
        the graftsort sorted rep and every graftview artifact registered
        under the column's token."""
        if self._sorted_rep is not None:
            from modin_tpu.ops.sorted_cache import invalidate

            invalidate(self)
        if self._view_token is not None:
            from modin_tpu.views import registry as views_registry

            views_registry.invalidate_column(self, reason="buffer")

    def spill(self) -> int:
        """Drop the device buffer, keeping an exact host copy; returns the
        device bytes freed (0 = not spillable right now)."""
        if self._data is None or self.is_lazy:
            return 0
        # a sorted rep derived from the buffer being dropped must not
        # outlive it (and holding it would defeat the spill anyway)
        self._invalidate_sorted()
        cache = self.host_cache
        if cache is None:
            # to_numpy round-trips the logical dtype exactly (and under
            # Downcast the f32 device value widens losslessly), so the
            # host copy reproduces the device buffer bit-for-bit
            cache = self.to_numpy()
        from modin_tpu.core.memory import device_ledger

        freed = device_ledger.deregister(self)
        # drop the buffer BEFORE registering the cache: is_spilled must be
        # True when the host ledger's enforce() runs, or a tight Memory
        # budget could evict the sole copy the moment it is registered
        self._data = None
        if self.host_cache is None:
            self.adopt_host_cache(cache)
        return freed

    # -- graftfuse: buffer donation ------------------------------------- #

    def donation_eligible(self) -> bool:
        """The LOCAL half of the donation proof: a concrete resident
        buffer with an exact host copy to restore from (the lineage-replay
        contract: after donation the column is *spilled*, and the next
        access transparently re-uploads).  The sole-consumer half comes
        from the device ledger — ``donation_safe`` for one column,
        ``buffer_consumer_counts`` for a whole dispatch's batch."""
        return (
            self._data is not None
            and not self.is_lazy
            and self.host_cache is not None
        )

    def donation_safe(self) -> bool:
        """Whether this column's buffer may ride in a donated jit position:
        :meth:`donation_eligible` plus the device ledger's proof that no
        OTHER live column holds the same buffer — donating a shared buffer
        would delete it under its other owner mid-use."""
        if not self.donation_eligible():
            return False
        from modin_tpu.core.memory import device_ledger

        return device_ledger.buffer_consumers(self._data) == 1

    def mark_donated(self) -> int:
        """Record that a donated dispatch consumed this column's buffer.

        The column becomes *spilled* (``_data is None`` with the exact host
        copy authoritative): every later read restores via lineage — a
        fresh upload — instead of touching the consumed buffer, which is
        exactly the use-after-donate guard.  Returns the device bytes
        released from the ledger (the HBM the donation reclaimed).
        """
        if self._data is None or self.is_lazy:
            return 0
        # a sorted rep derived from the consumed buffer must not outlive it
        self._invalidate_sorted()
        from modin_tpu.core.memory import device_ledger

        freed = device_ledger.deregister(self)
        self._data = None
        self.donated = True
        return freed

    def _restore(self) -> None:
        """Re-seat a spilled column's device buffer from its host copy."""
        if self.host_cache is None:
            raise RuntimeError(
                "spilled DeviceColumn has no host copy to restore from"
            )
        was_donated = self.donated  # reseat stamps the fresh buffer clean
        self.reseat_from_host()
        from modin_tpu.logging.metrics import emit_metric

        emit_metric("memory.device.restore", 1)
        if was_donated:
            # the use-after-donate guard doing its job: a buffer a fused
            # dispatch consumed was rebuilt via lineage on first re-access
            emit_metric("fuse.donated_restore", 1)

    def reseat_from_host(self) -> None:
        """Upload the exact host copy as a fresh device buffer (lineage
        kind 'host'; also the spill-restore path)."""
        values = self.host_cache  # single read: eviction may race us
        if values is None:
            raise RuntimeError("no host copy to re-seat from")
        self._invalidate_sorted()
        self._data = _device_put_values(np.asarray(values))
        self._register_device()

    def reseat_from_host_shard(self, shard_index: int) -> bool:
        """Re-seat ONLY one lost shard's slice from the exact host copy,
        keeping every live shard's device buffer (graftmesh single-shard
        recovery).  Returns False when not applicable — no host copy, a
        lazy/spilled column, a single-shard mesh, an uneven layout, or any
        failure reading the surviving shards (a real whole-device loss) —
        and the caller takes the full re-seat path instead.
        """
        values = self.host_cache  # single read: eviction may race us
        data = self._data
        if values is None or data is None or self.is_lazy:
            return False
        try:
            import jax

            from modin_tpu.parallel.mesh import num_row_shards

            S = num_row_shards()
            P = int(data.shape[0])
            if S < 2 or not (0 <= int(shard_index) < S) or P % S:
                return False
            L = P // S
            start = int(shard_index) * L
            # the ONE shared host->device transform (_device_layout_values,
            # exactly what a full upload applies), restricted to the lost
            # shard's row range (pad rows zero)
            dev_vals = _device_layout_values(np.asarray(values))
            sl = np.ascontiguousarray(dev_vals[start : start + L])
            if len(sl) < L:
                sl = np.concatenate(
                    [sl, np.zeros(L - len(sl), dtype=sl.dtype)]
                )
            by_start = {}
            for sh in data.addressable_shards:
                idx = sh.index[0]
                by_start[int(idx.start or 0)] = sh
            if len(by_start) != S or start not in by_start:
                return False
            arrays = []
            for st in sorted(by_start):
                sh = by_start[st]
                if st == start:
                    arrays.append(jax.device_put(sl, sh.device))
                else:
                    # touching a dead device's buffer raises here, which is
                    # exactly the signal to fall back to the full re-seat
                    arrays.append(sh.data)
            fresh = jax.make_array_from_single_device_arrays(
                data.shape, data.sharding, arrays
            )
        except Exception:  # graftlint: disable=EXC-HYGIENE -- the single-shard leg is an optimization; ANY failure (dead neighbor shards, exotic sharding) falls back to the whole-column re-seat
            return False
        self._invalidate_sorted()
        self._data = fresh
        self._register_device()
        return True

    def shard_valid_counts(self) -> np.ndarray:
        """Per-shard valid-row counts under the padded prefix layout:
        leading shards are full, one shard is ragged, trailing pad shards
        are empty.  The per-shard valid-row accounting of the SPMD layout
        (docs/architecture.md "SPMD execution & the mesh substrate"): the
        padded-bytes ledger splits evenly, this answers how much of each
        shard's slice is live data.

        Uses the concrete buffer's physical length when it divides the
        current shard count; a buffer laid out under a different mesh (or
        a lazy/spilled column) answers for the canonical current-mesh
        padding instead.
        """
        from modin_tpu.ops.structural import pad_len
        from modin_tpu.parallel.mesh import num_row_shards

        S = max(num_row_shards(), 1)
        data = self._data
        P = (
            int(data.shape[0])
            if data is not None and hasattr(data, "shape")
            else pad_len(self.length)
        )
        if P % S:
            P = pad_len(self.length)
        L = P // S
        return np.clip(
            self.length - np.arange(S, dtype=np.int64) * L, 0, L
        )

    def adopt_reseated(self, data: Any) -> None:
        """Adopt a lineage-replayed device buffer (op-replay recovery)."""
        self._invalidate_sorted()
        self._data = data
        self._register_device()

    def adopt_host_cache(self, values: np.ndarray) -> None:
        """Take ``values`` as the exact host copy (registered against the
        host-memory budget like every other cache)."""
        self.host_cache = values
        from modin_tpu.core.memory import ledger

        ledger.register(self)

    def host_checkpoint(self) -> None:
        """Pin the exact host copy (lineage depth cut-point): one fetch now
        makes this column depth-0 recoverable forever after."""
        if self.host_cache is None:
            self.adopt_host_cache(self.to_numpy())

    @classmethod
    def from_numpy(cls, values: np.ndarray, sharding: Any = None) -> "DeviceColumn":
        return cls(
            _device_put_values(values, sharding),
            values.dtype,
            length=len(values),
            host_cache=values,
        )

    def to_numpy(self) -> np.ndarray:
        from modin_tpu.parallel.engine import JaxWrapper

        cache = self.host_cache  # single read: eviction may race us
        if cache is not None:
            from modin_tpu.core.memory import ledger

            ledger.touch(self)
            return cache
        try:
            values = np.asarray(JaxWrapper.materialize(self.data))[: self.length]
        except Exception as err:  # graftlint: disable=EXC-HYGIENE -- recovery gate: recover_for_read re-seats only on a classified DeviceLost and this re-raises otherwise
            from modin_tpu.core.execution.recovery import recover_for_read

            if not recover_for_read(self, err):
                raise
            # the column was re-seated from lineage: one fetch retry
            values = np.asarray(JaxWrapper.materialize(self.data))[: self.length]
        if self.pandas_dtype.kind in "mM":
            values = values.view(self.pandas_dtype)
        elif values.dtype != self.pandas_dtype:
            # Float64Policy=Downcast stores f32 on device for a logical f64
            values = values.astype(self.pandas_dtype)
        return values

    def with_data(
        self,
        data: Any,
        pandas_dtype: Optional[np.dtype] = None,
        length: Optional[int] = None,
    ) -> "DeviceColumn":
        return DeviceColumn(
            data,
            pandas_dtype if pandas_dtype is not None else self.pandas_dtype,
            length if length is not None else self.length,
        )

    def __len__(self) -> int:
        return self.length


class HostColumn:
    """One column kept on host (object/string/categorical/extension dtypes).

    ``_dict_cache`` lazily holds the column's dictionary encoding — (codes
    DeviceColumn, SORTED categories) — or False once found unencodable (see
    ops/dictionary.py).  ``_cat_cache`` is the separate cache for the
    categorical-dtype encoding, whose categories keep CATEGORY order — the
    two orderings must never be served to each other's consumers.  Columns
    are replaced, never mutated in place, so the caches cannot go stale.
    """

    # __weakref__: graftview host-identity guards (views/groupby_cache.py)
    # pin cached results to the exact live column objects via weakrefs
    __slots__ = ("data", "_dict_cache", "_cat_cache", "__weakref__")
    is_device = False

    def __init__(self, data: Any):
        # data: 1-D numpy array or pandas ExtensionArray (unpadded)
        self.data = data
        self._dict_cache = None
        self._cat_cache = None

    @property
    def pandas_dtype(self):
        return self.data.dtype

    @property
    def length(self) -> int:
        return len(self.data)

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.data)

    def to_pandas_array(self) -> Any:
        return self.data

    def __len__(self) -> int:
        return len(self.data)


Column = Union[DeviceColumn, HostColumn]


class TpuDataframe(BaseDataframe, ClassLogger, modin_layer="CORE-FRAME"):
    """Columnar frame: host metadata + device/host column store.

    Implements the abstract structural algebra
    (core/dataframe/base/dataframe.py BaseDataframe; reference
    modin/core/dataframe/base/dataframe/dataframe.py:26)."""

    def __init__(
        self,
        columns: List[Column],
        col_labels: pandas.Index,
        index: Union[pandas.Index, LazyIndex, Callable],
        nrows: Optional[int] = None,
    ):
        self._columns = columns
        self._col_labels = ensure_index(col_labels)
        if not isinstance(index, LazyIndex):
            index = LazyIndex(index, nrows)
        self._index = index

    # ------------------------------------------------------------------ #
    # Construction / materialization
    # ------------------------------------------------------------------ #

    @classmethod
    def from_pandas(cls, df: pandas.DataFrame) -> "TpuDataframe":
        from modin_tpu.core.execution.resilience import DeviceFailure

        columns: List[Column] = []
        for i in range(df.shape[1]):
            series = df.iloc[:, i]
            dtype = series.dtype
            if isinstance(dtype, np.dtype) and _is_device_dtype(dtype):
                values = series.to_numpy()
                try:
                    columns.append(DeviceColumn.from_numpy(values))
                except DeviceFailure:
                    # upload failed (device OOM / lost): keep the column on
                    # host — every device path declines host columns and the
                    # pandas defaults answer, so ingest degrades instead of
                    # crashing (the engine seam already emitted the metric).
                    # The raw ndarray, NOT series.array: a
                    # NumpyExtensionArray's NumpyEADtype compares unequal to
                    # the np.dtype every dispatch check expects.
                    columns.append(HostColumn(values))
            else:
                arr = series.array.copy()
                if isinstance(arr, pandas.arrays.NumpyExtensionArray):
                    # store the raw ndarray: NumpyEADtype('object') fails ==
                    # against np.dtype(object) and would leak to users as a
                    # different-looking dtype
                    arr = np.asarray(arr)
                columns.append(HostColumn(arr))
        return cls(columns, df.columns, df.index, nrows=len(df))

    def to_pandas(self) -> pandas.DataFrame:
        self.materialize_device()
        idx = self.index
        data = {}
        for i, col in enumerate(self._columns):
            if col.is_device:
                data[i] = col.to_numpy()
            else:
                arr = col.to_pandas_array()
                if _is_object_dtype(getattr(arr, "dtype", None)):
                    # pandas 3 infers str for plain object string arrays;
                    # an explicit-dtype Series is the only construction
                    # that round-trips object EXACTLY
                    arr = pandas.Series(arr, index=idx, dtype=object)
                data[i] = arr
        df = pandas.DataFrame(data, index=idx, copy=False)
        df.columns = self._col_labels
        return df

    def to_numpy(self, **kwargs: Any) -> np.ndarray:
        return self.to_pandas().to_numpy(**kwargs)

    # ------------------------------------------------------------------ #
    # Metadata
    # ------------------------------------------------------------------ #

    @property
    def index(self) -> pandas.Index:
        return self._index.get()

    @index.setter
    def index(self, value: Any) -> None:
        value = ensure_index(value)
        assert len(value) == len(self), "Length mismatch"
        self._index = LazyIndex(value)

    @property
    def columns(self) -> pandas.Index:
        return self._col_labels

    @columns.setter
    def columns(self, value: Any) -> None:
        value = ensure_index(value)
        assert len(value) == len(self._columns), "Length mismatch"
        self._col_labels = value

    @property
    def dtypes(self) -> pandas.Series:
        return pandas.Series(
            [col.pandas_dtype for col in self._columns], index=self._col_labels
        )

    def __len__(self) -> int:
        if self._columns:
            return self._columns[0].length
        return len(self.index)

    @property
    def num_cols(self) -> int:
        return len(self._columns)

    @property
    def all_device(self) -> bool:
        return all(col.is_device for col in self._columns)

    def copy(self) -> "TpuDataframe":
        return TpuDataframe(
            list(self._columns), self._col_labels, self._index.copy()
        )

    def materialize_device(self) -> None:
        """Batch-materialize all deferred device columns in ONE fused jit.

        Multi-column consumers call this before touching ``.data`` so a frame
        of N lazy columns costs one dispatch, not N (the one-jit-per-operator
        invariant, extended to the fusion layer).
        """
        from modin_tpu.ops.lazy import materialize_exprs

        lazy_cols = [c for c in self._columns if c.is_device and c.is_lazy]
        if not lazy_cols:
            return
        results = materialize_exprs([c.raw for c in lazy_cols])
        for col, value in zip(lazy_cols, results):
            col._data = value
            col._on_materialized()

    def finalize(self) -> None:
        """Block until device work for this frame completes (one sync).

        Columns with a ``host_cache`` are skipped: their values are already
        known on the host (the device buffer is a pending *upload*, not
        pending compute), so there is nothing observable to wait for — any
        downstream device op consuming the buffer orders after the transfer
        on-device.  Blocking on them costs a full tunnel round-trip per call
        on remote TPU for no information.
        """
        from modin_tpu.parallel.engine import JaxWrapper

        self.materialize_device()
        device_data = [
            col.data
            for col in self._columns
            if col.is_device and col.host_cache is None
        ]
        if device_data:
            JaxWrapper.wait(device_data)

    def free(self) -> None:
        self._columns = []

    # ------------------------------------------------------------------ #
    # Structural algebra (host-metadata ops are free; device ops dispatch
    # one jit per frame, fused across columns)
    # ------------------------------------------------------------------ #

    def select_columns_by_position(self, positions: Sequence[int]) -> "TpuDataframe":
        return TpuDataframe(
            [self._columns[i] for i in positions],
            self._col_labels[list(positions)],
            self._index,
        )

    def rename_columns(self, new_labels: pandas.Index) -> "TpuDataframe":
        return TpuDataframe(list(self._columns), new_labels, self._index)

    def with_columns(
        self,
        columns: List[Column],
        col_labels: Optional[pandas.Index] = None,
        index: Optional[Union[pandas.Index, LazyIndex]] = None,
        nrows: Optional[int] = None,
    ) -> "TpuDataframe":
        return TpuDataframe(
            columns,
            col_labels if col_labels is not None else self._col_labels,
            index if index is not None else self._index,
            nrows=nrows,
        )

    def take_rows_positional(self, positions: Any) -> "TpuDataframe":
        """Gather rows by position (pad-aware device gather, one jit)."""
        n = len(self)
        if isinstance(positions, slice):
            positions = np.arange(*positions.indices(n), dtype=np.int64)
        else:
            positions = np.asarray(positions, dtype=np.int64)
            positions = np.where(positions < 0, positions + n, positions)
        return self._take_host_positions(positions)

    def _take_host_positions(self, pos_arr: np.ndarray) -> "TpuDataframe":
        from modin_tpu.ops.structural import gather_columns

        self.materialize_device()
        device_idx = [i for i, c in enumerate(self._columns) if c.is_device]
        new_columns: List[Column] = list(self._columns)
        if device_idx:
            datas, n_out = gather_columns(
                [self._columns[i].data for i in device_idx], pos_arr
            )
            for i, d in zip(device_idx, datas):
                col = self._columns[i]
                src = col.host_cache  # single read: eviction may race us
                cache = src.take(pos_arr) if src is not None else None
                new_columns[i] = DeviceColumn(
                    d, col.pandas_dtype, length=len(pos_arr), host_cache=cache
                )
        for i, col in enumerate(self._columns):
            if not col.is_device:
                new_columns[i] = HostColumn(col.data.take(pos_arr))
        new_index = self._index.map_after(lambda idx: idx.take(pos_arr), len(pos_arr))
        return self.with_columns(new_columns, index=new_index, nrows=len(pos_arr))

    def filter_rows_mask(self, mask: Any) -> "TpuDataframe":
        """Boolean-mask rows.  The row count is data-dependent, so this is an
        eager (synchronizing) operation — the reference has the same property
        via lazy row-length caches (dataframe.py:242-343)."""
        from modin_tpu.ops.structural import pad_len
        from modin_tpu.parallel.engine import JaxWrapper

        if JaxWrapper.is_future(mask):
            # device-produced mask: fetch through the seam so the blocking
            # transfer gets the resilience policy (classify/retry/watchdog)
            mask = JaxWrapper.materialize(mask)
        mask_np = np.asarray(mask)
        n = len(self)
        if len(mask_np) == pad_len(n):
            # Device-produced masks carry shard padding; padded tail is dead.
            mask_np = mask_np[:n]
        elif len(mask_np) != n:
            raise ValueError(
                f"Item wrong length {len(mask_np)} instead of {n}."
            )
        positions = np.nonzero(mask_np)[0]
        return self._take_host_positions(positions)

    def filter_rows_mask_device(self, mask_raw: Any) -> "TpuDataframe":
        """Boolean-filter rows entirely on device (mask may be deferred).

        The mask computation fuses into the compaction kernel and the only
        host sync is the scalar kept-count; positions never round-trip
        through the host for device columns (the reference keeps lazy row
        counts for the same reason, ref dataframe.py:242-343).  Host columns
        and the row index resolve through one lazy positions fetch.
        """
        from modin_tpu.ops.structural import compact_rows
        from modin_tpu.parallel.engine import JaxWrapper

        from modin_tpu.ops.lazy import lazy_op
        from modin_tpu.ops.structural import pad_len

        device_idx = [i for i, c in enumerate(self._columns) if c.is_device]
        datas, count, perm = compact_rows(
            [self._columns[i].raw for i in device_idx], mask_raw, len(self)
        )
        n_out = int(JaxWrapper.materialize(count))
        # restore the padded-column invariant (physical size = pad_len(n)):
        # compaction kept the input's physical size, so trim to the output's.
        # The trim stays DEFERRED (one LazyExpr node per column): a consuming
        # reduction fuses it into its own program, so a filter->agg pipeline
        # costs two dispatches total (compact, fused trim+reduce) instead of
        # three; any other consumer batch-materializes the trims in one jit.
        p_out = pad_len(n_out)
        if datas and datas[0].shape[0] != p_out:
            datas = [
                lazy_op("trim", d, static=(("p_out", int(p_out)),)) for d in datas
            ]
        new_columns: List[Column] = list(self._columns)
        for i, d in zip(device_idx, datas):
            col = self._columns[i]
            new_columns[i] = DeviceColumn(d, col.pandas_dtype, length=n_out)

        host_positions_cache: dict = {}

        def host_positions() -> np.ndarray:
            if "pos" not in host_positions_cache:
                host_positions_cache["pos"] = np.asarray(
                    JaxWrapper.materialize(perm)
                )[:n_out]
            return host_positions_cache["pos"]

        for i, col in enumerate(self._columns):
            if not col.is_device:
                new_columns[i] = HostColumn(col.data.take(host_positions()))
        new_index = self._index.map_after(
            lambda idx: idx.take(host_positions()), n_out
        )
        return self.with_columns(new_columns, index=new_index, nrows=n_out)

    def concat_rows(self, others: List["TpuDataframe"]) -> "TpuDataframe":
        """Row-wise concat when column labels/dtypes align exactly."""
        from modin_tpu.ops.structural import concat_columns

        frames = [self, *others]
        for f in frames:
            f.materialize_device()
        lengths = [len(f) for f in frames]
        total = sum(lengths)
        device_ok = [
            all(f._columns[ci].is_device for f in frames)
            and len({f._columns[ci].data.dtype for f in frames}) == 1
            for ci in range(self.num_cols)
        ]
        new_columns: List[Column] = [None] * self.num_cols
        device_cis = [ci for ci in range(self.num_cols) if device_ok[ci]]
        from modin_tpu import views as graftview

        if device_cis:
            parts = [[f._columns[ci].data for ci in device_cis] for f in frames]
            datas, n_out = concat_columns(parts, lengths)
            for ci, d in zip(device_cis, datas):
                cols = [f._columns[ci] for f in frames]
                # single read per column: eviction may race us
                caches = [c.host_cache for c in cols]
                cache = None
                if all(c is not None for c in caches):
                    cache = np.concatenate(caches)
                new_col = DeviceColumn(
                    d, cols[0].pandas_dtype, length=total, host_cache=cache
                )
                if graftview.VIEWS_ON:
                    # graftview append link: the new column's first
                    # len(self) rows ARE self's column — artifacts built
                    # from it fold only the appended tail on the next query
                    from modin_tpu.views import registry as views_registry

                    views_registry.note_append(new_col, cols[0])
                new_columns[ci] = new_col
        for ci in range(self.num_cols):
            if device_ok[ci]:
                continue
            values = np.concatenate(
                [np.asarray(f._columns[ci].to_numpy()) for f in frames]
            )
            if all(f._columns[ci].is_device for f in frames):
                new_columns[ci] = DeviceColumn.from_numpy(values)
            else:
                dtypes = {f._columns[ci].pandas_dtype for f in frames}
                if len(dtypes) == 1:
                    # keep the exact dtype: re-inference would e.g. turn the
                    # pandas-3 'str' dtype into the 'string' extension dtype
                    arr = pandas.array(values, dtype=next(iter(dtypes)))
                else:
                    arr = pandas.array(values)
                if isinstance(arr, pandas.arrays.NumpyExtensionArray):
                    # store the raw ndarray, exactly like from_pandas: a
                    # NumpyEADtype('object') compares unequal to the
                    # np.dtype(object) every dispatch check expects, which
                    # would make a CHAINED concat fail the dtype-equality
                    # gate and fall back to pandas
                    arr = np.asarray(arr)
                new_columns[ci] = HostColumn(arr)
                if (
                    graftview.VIEWS_ON
                    and getattr(self._columns[ci], "_dict_cache", None)
                    not in (None, False)
                ):
                    # graftview dictionary maintenance: the prefix already
                    # paid its factorize — extend the code table with only
                    # the appended tail instead of re-encoding n_out rows
                    # on the next string groupby/nunique
                    from modin_tpu.views.incremental import extend_dict_encoding

                    ext = extend_dict_encoding(
                        self._columns[ci], values[lengths[0]:]
                    )
                    if ext is not None:
                        new_columns[ci]._dict_cache = ext
                        from modin_tpu.logging.metrics import emit_metric

                        emit_metric("view.fold", 1)
        lazies = [f._index for f in frames]

        def build_index() -> pandas.Index:
            return lazies[0].get().append([lz.get() for lz in lazies[1:]])

        return self.with_columns(
            new_columns, index=LazyIndex(build_index, total), nrows=total
        )

    def get_column(self, position: int) -> Column:
        return self._columns[position]

    def column_position(self, label: Any) -> List[int]:
        return list(self._col_labels.get_indexer_for([label]))
