"""DataFrame interchange protocol over native column buffers.

Reference design: modin/core/dataframe/pandas/interchange/ (2,228 LoC)
produces protocol objects over the partitioned pandas frame.  Here the
producer sits directly on ``TpuDataframe``:

- a device column with an intact ``host_cache`` exports its buffer
  ZERO-COPY over that numpy array (no pandas frame is ever built);
- a computed device column fetches exactly once, per *requested* column —
  a consumer selecting 2 of 50 columns transfers 2;
- host (string/categorical/extension) columns delegate to pandas' own
  protocol column for the complex variable-width layouts.

Numeric/bool columns use NaN (floats) or are non-nullable (ints/bools);
datetimes export the int64 NaT sentinel, which is exactly the protocol's
USE_SENTINEL encoding.
"""

from __future__ import annotations

import enum
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import pandas


class DtypeKind(enum.IntEnum):
    INT = 0
    UINT = 1
    FLOAT = 2
    BOOL = 20
    STRING = 21
    DATETIME = 22
    CATEGORICAL = 23


class ColumnNullType(enum.IntEnum):
    NON_NULLABLE = 0
    USE_NAN = 1
    USE_SENTINEL = 2
    USE_BITMASK = 3
    USE_BYTEMASK = 4


_NP_TO_ARROW_FMT = {
    "int8": "c", "int16": "s", "int32": "i", "int64": "l",
    "uint8": "C", "uint16": "S", "uint32": "I", "uint64": "L",
    "float32": "f", "float64": "g", "bool": "b",
}

_NAT = np.iinfo(np.int64).min


class TpuBuffer:
    """Protocol buffer over a (host) numpy array — zero-copy view."""

    def __init__(self, array: np.ndarray, allow_copy: bool = True):
        if not array.flags.c_contiguous:
            if not allow_copy:
                raise RuntimeError(
                    "non-contiguous buffer requires a copy (allow_copy=False)"
                )
            array = np.ascontiguousarray(array)
        self._array = array

    @property
    def bufsize(self) -> int:
        return self._array.nbytes

    @property
    def ptr(self) -> int:
        return self._array.__array_interface__["data"][0]

    def __dlpack__(self):
        return self._array.__dlpack__()

    def __dlpack_device__(self) -> Tuple[int, int]:
        return (1, 0)  # kDLCPU

    def __repr__(self) -> str:
        return f"TpuBuffer(size={self.bufsize}, ptr={self.ptr:#x})"


class TpuColumnXchg:
    """Protocol column over one TpuDataframe column."""

    def __init__(self, column: Any, allow_copy: bool = True):
        self._column = column
        self._allow_copy = allow_copy
        self._values: Optional[np.ndarray] = None

    def _data(self) -> np.ndarray:
        if self._values is None:
            # host_cache is returned as-is by to_numpy: zero-copy when cached,
            # one device fetch otherwise
            self._values = self._column.to_numpy()
        return self._values

    def size(self) -> int:
        return len(self._column)

    @property
    def offset(self) -> int:
        return 0

    @property
    def dtype(self) -> Tuple[DtypeKind, int, str, str]:
        dt = np.dtype(self._column.pandas_dtype)
        if dt.kind == "M":
            unit = np.datetime_data(dt)[0]
            return (DtypeKind.DATETIME, 64, f"ts{unit[0]}:", "=")
        if dt.kind == "m":
            unit = np.datetime_data(dt)[0]
            return (DtypeKind.DATETIME, 64, f"tD{unit[0]}", "=")
        kind = {
            "i": DtypeKind.INT, "u": DtypeKind.UINT, "f": DtypeKind.FLOAT,
            "b": DtypeKind.BOOL,
        }[dt.kind]
        return (kind, dt.itemsize * 8, _NP_TO_ARROW_FMT[dt.name], "=")

    @property
    def describe_categorical(self) -> dict:
        raise TypeError("not a categorical column")

    @property
    def describe_null(self) -> Tuple[int, Any]:
        dt = np.dtype(self._column.pandas_dtype)
        if dt.kind == "f":
            return (ColumnNullType.USE_NAN, None)
        if dt.kind in "mM":
            return (ColumnNullType.USE_SENTINEL, _NAT)
        return (ColumnNullType.NON_NULLABLE, None)

    @property
    def null_count(self) -> int:
        dt = np.dtype(self._column.pandas_dtype)
        if dt.kind == "f":
            return int(np.isnan(self._data()).sum())
        if dt.kind in "mM":
            return int((self._data().view("int64") == _NAT).sum())
        return 0

    @property
    def metadata(self) -> Dict[str, Any]:
        return {}

    def num_chunks(self) -> int:
        return 1

    def get_chunks(self, n_chunks: Optional[int] = None) -> Iterable["TpuColumnXchg"]:
        yield self

    def get_buffers(self) -> Dict[str, Any]:
        values = self._data()
        if values.dtype.kind in "mM":
            values = values.view("int64")
        return {
            "data": (TpuBuffer(values, self._allow_copy), self.dtype),
            "validity": None,
            "offsets": None,
        }


class TpuDataFrameXchg:
    """Protocol dataframe over a TpuDataframe (lazy, per-column buffers)."""

    version = 0

    def __init__(
        self,
        modin_frame: Any,
        nan_as_null: bool = False,
        allow_copy: bool = True,
    ):
        self._frame = modin_frame
        self._nan_as_null = nan_as_null
        self._allow_copy = allow_copy

    def __dataframe__(self, nan_as_null: bool = False, allow_copy: bool = True):
        return TpuDataFrameXchg(self._frame, nan_as_null, allow_copy)

    @property
    def metadata(self) -> Dict[str, Any]:
        # consumers (pandas included) restore the index from "pandas.index"
        return {"pandas.index": self._frame.index}

    def num_columns(self) -> int:
        return self._frame.num_cols

    def num_rows(self) -> int:
        return len(self._frame)

    def num_chunks(self) -> int:
        return 1

    def column_names(self) -> List[Any]:
        return list(self._frame.columns)

    def _make_column(self, position: int):
        col = self._frame._columns[position]
        if col.is_device:
            return TpuColumnXchg(col, self._allow_copy)
        # host (string/categorical/extension) columns: pandas' own protocol
        # column handles variable-width layouts; one column, not the frame
        label = self._frame.columns[position]
        return (
            pandas.DataFrame({label: col.to_pandas_array()})
            .__dataframe__(self._nan_as_null, self._allow_copy)
            .get_column(0)
        )

    def get_column(self, i: int):
        return self._make_column(i)

    def get_column_by_name(self, name: str):
        positions = self._frame.column_position(name)
        return self._make_column(positions[0])

    def get_columns(self) -> List[Any]:
        return [self._make_column(i) for i in range(self._frame.num_cols)]

    def select_columns(self, indices: Sequence[int]) -> "TpuDataFrameXchg":
        return TpuDataFrameXchg(
            self._frame.select_columns_by_position([int(i) for i in indices]),
            self._nan_as_null,
            self._allow_copy,
        )

    def select_columns_by_name(self, names: Sequence[str]) -> "TpuDataFrameXchg":
        positions = [self._frame.column_position(n)[0] for n in names]
        return self.select_columns(positions)

    def get_chunks(self, n_chunks: Optional[int] = None) -> Iterable["TpuDataFrameXchg"]:
        if not n_chunks or n_chunks <= 1:
            yield self
            return
        # the spec requires EXACTLY n_chunks chunks (trailing ones may be
        # short or empty), matching the pandas producer's stepping
        n = len(self._frame)
        step = n // n_chunks
        if n % n_chunks:
            step += 1
        for start in range(0, max(step, 1) * n_chunks, max(step, 1)):
            yield TpuDataFrameXchg(
                self._frame.take_rows_positional(slice(start, min(start + step, n))),
                self._nan_as_null,
                self._allow_copy,
            )
