"""modin_tpu subpackage."""
