"""``DefaultMethod`` — build a QC method from any pandas callable by materializing.

Reference design: /root/reference/modin/core/dataframe/algebra/default2pandas/default.py:56.
This is the correctness backstop of the whole framework: every query-compiler
operation has a default implementation that gathers the frame to host pandas,
applies the pandas kernel, and re-wraps the result.  Device-native compilers
override the hot subset; everything else stays correct from day one.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import pandas

from modin_tpu.error_message import ErrorMessage
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL


class ObjTypeDeterminer:
    """Pass-through target: look the function up on the object itself."""

    def __getattr__(self, key: str) -> Callable:
        def func(df: Any, *args: Any, **kwargs: Any) -> Any:
            return getattr(df, key)(*args, **kwargs)

        return func


class DefaultMethod:
    """Builder of default-to-pandas query-compiler methods.

    ``register(func)`` returns a ``caller(query_compiler, *args, **kwargs)``
    that materializes, applies ``func`` against the (possibly accessor-wrapped)
    pandas object, and wraps DataFrame/Series results back into a QC.
    """

    OBJECT_TYPE = "DataFrame"
    # the pandas class the registered function is applied against
    DEFAULT_OBJECT_TYPE = pandas.DataFrame

    @classmethod
    def frame_wrapper(cls, df: pandas.DataFrame) -> Any:
        """Extract the object to apply the function against (df, series, accessor...)."""
        return df

    @classmethod
    def get_func(cls, func: Union[str, property, Callable], obj_type: Any) -> Callable:
        if isinstance(func, str):
            fn = getattr(obj_type, func, None)
            if fn is None:
                fn = getattr(ObjTypeDeterminer(), func)
            func = fn
        if isinstance(func, property):
            fget = func.fget

            def applyier(df: Any, *args: Any, **kwargs: Any) -> Any:
                return fget(df)

            return applyier
        if not callable(func):
            raise TypeError(f"Cannot build a default method from {func!r}")
        return func

    @classmethod
    def register(
        cls,
        func: Union[str, property, Callable],
        obj_type: Optional[Any] = None,
        inplace: Optional[bool] = None,
        fn_name: Optional[str] = None,
        squeeze_self: bool = False,
    ) -> Callable:
        """Build a QC-level default method applying ``func`` via host pandas."""
        if obj_type is None:
            obj_type = cls.DEFAULT_OBJECT_TYPE
        fn = cls.get_func(func, obj_type)
        fn_display_name = fn_name or getattr(
            func, "__name__", getattr(fn, "__name__", str(func))
        )

        def caller(query_compiler: Any, *args: Any, **kwargs: Any) -> Any:
            df = query_compiler.to_pandas()
            if squeeze_self:
                df = df.squeeze(axis=1)
            target = cls.frame_wrapper(df)
            ErrorMessage.default_to_pandas(
                f"`{cls.OBJECT_TYPE}.{fn_display_name}`"
            )
            result = fn(target, *args, **kwargs)
            if inplace or (inplace is None and result is None):
                result = df
            return cls.build_output(query_compiler, result)

        caller.__name__ = fn_display_name
        # generated straight from the pandas callable: safe to invoke with
        # pandas-signature args (the routing tables key off this marker)
        caller._pandas_signature_default = True
        return caller

    @classmethod
    def build_output(cls, query_compiler: Any, result: Any) -> Any:
        """Wrap a pandas result back into a query compiler when 2-D/1-D."""
        was_series = isinstance(result, pandas.Series)
        if was_series:
            name = result.name if result.name is not None else MODIN_UNNAMED_SERIES_LABEL
            result = result.to_frame(name)
        if isinstance(result, pandas.DataFrame):
            out = query_compiler.__constructor__.from_pandas(
                result, type(query_compiler._modin_frame)
                if hasattr(query_compiler, "_modin_frame")
                else None
            )
            if was_series:
                # consumers (API fallback routing) wrap hint=="column" results
                # back as Series
                out._shape_hint = "column"
            return out
        return result


class DataFrameDefault(DefaultMethod):
    OBJECT_TYPE = "DataFrame"
    DEFAULT_OBJECT_TYPE = pandas.DataFrame


class SeriesDefault(DefaultMethod):
    OBJECT_TYPE = "Series"
    DEFAULT_OBJECT_TYPE = pandas.Series

    @classmethod
    def frame_wrapper(cls, df: pandas.DataFrame) -> pandas.Series:
        series = df.squeeze(axis=1)
        if (
            isinstance(series, pandas.Series)
            and series.name == MODIN_UNNAMED_SERIES_LABEL
        ):
            # the internal placeholder must not leak into results that carry
            # the series name (e.g. value_counts' index name)
            series = series.rename(None)
        return series


class StrDefault(SeriesDefault):
    OBJECT_TYPE = "Series.str"
    DEFAULT_OBJECT_TYPE = pandas.core.strings.accessor.StringMethods

    @classmethod
    def frame_wrapper(cls, df: pandas.DataFrame) -> Any:
        return df.squeeze(axis=1).str


class DateTimeDefault(SeriesDefault):
    OBJECT_TYPE = "Series.dt"
    DEFAULT_OBJECT_TYPE = pandas.core.indexes.accessors.CombinedDatetimelikeProperties

    @classmethod
    def frame_wrapper(cls, df: pandas.DataFrame) -> Any:
        return df.squeeze(axis=1).dt


class CatDefault(SeriesDefault):
    OBJECT_TYPE = "Series.cat"
    DEFAULT_OBJECT_TYPE = pandas.core.arrays.categorical.CategoricalAccessor

    @classmethod
    def frame_wrapper(cls, df: pandas.DataFrame) -> Any:
        return df.squeeze(axis=1).cat


class _AccessorLookupOnly:
    """Sentinel DEFAULT_OBJECT_TYPE: forces string funcs through
    ObjTypeDeterminer so names that collide with pandas.Series methods
    (``__getitem__``, ``explode``...) resolve on the ACCESSOR object."""


class ListDefault(SeriesDefault):
    OBJECT_TYPE = "Series.list"
    DEFAULT_OBJECT_TYPE = _AccessorLookupOnly

    @classmethod
    def frame_wrapper(cls, df: pandas.DataFrame) -> Any:
        return df.squeeze(axis=1).list


class StructDefault(SeriesDefault):
    OBJECT_TYPE = "Series.struct"
    DEFAULT_OBJECT_TYPE = _AccessorLookupOnly

    @classmethod
    def frame_wrapper(cls, df: pandas.DataFrame) -> Any:
        return df.squeeze(axis=1).struct


class RollingDefault(DefaultMethod):
    """Defaults for rolling-window aggregations (fold-shaped ops)."""

    OBJECT_TYPE = "Rolling"

    @classmethod
    def register(cls, func: Union[str, Callable], squeeze_self: bool = False, **kw: Any) -> Callable:
        fn_name = kw.get("fn_name") or (
            func if isinstance(func, str) else getattr(func, "__name__", str(func))
        )

        def caller(
            query_compiler: Any, rolling_kwargs: dict, *args: Any, **kwargs: Any
        ) -> Any:
            from modin_tpu.utils import qc_to_pandas_for_write

            # series-shaped compilers run through Series.rolling so
            # pandas' own result shapes/naming apply (cov/corr vs a Series)
            df = qc_to_pandas_for_write(query_compiler)
            if squeeze_self and isinstance(df, pandas.DataFrame):
                df = df.squeeze(axis=1)
            ErrorMessage.default_to_pandas(f"`Rolling.{fn_name}`")
            roller = df.rolling(**rolling_kwargs)
            fn = getattr(type(roller), func) if isinstance(func, str) else func
            return cls.build_output(query_compiler, fn(roller, *args, **kwargs))

        caller.__name__ = f"rolling_{fn_name}"
        return caller


class ExpandingDefault(DefaultMethod):
    OBJECT_TYPE = "Expanding"

    @classmethod
    def register(cls, func: Union[str, Callable], squeeze_self: bool = False, **kw: Any) -> Callable:
        fn_name = kw.get("fn_name") or (
            func if isinstance(func, str) else getattr(func, "__name__", str(func))
        )

        def caller(
            query_compiler: Any, expanding_args: list, *args: Any, **kwargs: Any
        ) -> Any:
            from modin_tpu.utils import qc_to_pandas_for_write

            # series-shaped compilers run through Series.expanding so
            # pandas' own result shapes/naming apply (cov/corr vs a Series)
            df = qc_to_pandas_for_write(query_compiler)
            if squeeze_self and isinstance(df, pandas.DataFrame):
                df = df.squeeze(axis=1)
            ErrorMessage.default_to_pandas(f"`Expanding.{fn_name}`")
            roller = df.expanding(*expanding_args)
            fn = getattr(type(roller), func) if isinstance(func, str) else func
            return cls.build_output(query_compiler, fn(roller, *args, **kwargs))

        caller.__name__ = f"expanding_{fn_name}"
        return caller


class EwmDefault(DefaultMethod):
    """Defaults for exponentially-weighted-window aggregations
    (reference modin/pandas/window.py ExponentialMovingWindow)."""

    OBJECT_TYPE = "Ewm"

    @classmethod
    def register(cls, func: Union[str, Callable], squeeze_self: bool = False, **kw: Any) -> Callable:
        fn_name = kw.get("fn_name") or (
            func if isinstance(func, str) else getattr(func, "__name__", str(func))
        )

        def caller(
            query_compiler: Any, ewm_kwargs: dict, *args: Any, **kwargs: Any
        ) -> Any:
            from modin_tpu.utils import qc_to_pandas_for_write, try_cast_to_pandas

            # series-shaped compilers run through Series.ewm so pandas' own
            # result-naming conventions apply (cov/corr vs another Series)
            df = qc_to_pandas_for_write(query_compiler)
            if squeeze_self and isinstance(df, pandas.DataFrame):
                df = df.squeeze(axis=1)
            ErrorMessage.default_to_pandas(f"`ExponentialMovingWindow.{fn_name}`")
            roller = df.ewm(**ewm_kwargs)
            fn = getattr(type(roller), func) if isinstance(func, str) else func
            # raw compilers may arrive as `other` from the device pair path
            args = try_cast_to_pandas(args, squeeze=True)
            kwargs = try_cast_to_pandas(kwargs, squeeze=True)
            return cls.build_output(query_compiler, fn(roller, *args, **kwargs))

        caller.__name__ = f"ewm_{fn_name}"
        return caller


class ResampleDefault(DefaultMethod):
    OBJECT_TYPE = "Resampler"

    @classmethod
    def register(cls, func: Union[str, Callable], squeeze_self: bool = False, **kw: Any) -> Callable:
        fn_name = kw.get("fn_name") or (
            func if isinstance(func, str) else getattr(func, "__name__", str(func))
        )

        def caller(
            query_compiler: Any, resample_kwargs: dict, *args: Any, **kwargs: Any
        ) -> Any:
            df = query_compiler.to_pandas()
            if squeeze_self or query_compiler._shape_hint == "column":
                # a Series resample must run as a SERIES: frame resample
                # changes result shapes (ohlc -> MultiIndex columns)
                df = df.squeeze(axis=1)
                if (
                    isinstance(df, pandas.Series)
                    and df.name == MODIN_UNNAMED_SERIES_LABEL
                ):
                    df = df.rename(None)
            ErrorMessage.default_to_pandas(f"`Resampler.{fn_name}`")
            resampler = df.resample(**resample_kwargs)
            fn = getattr(type(resampler), func) if isinstance(func, str) else func
            return cls.build_output(query_compiler, fn(resampler, *args, **kwargs))

        caller.__name__ = f"resample_{fn_name}"
        return caller


class GroupByDefault(DefaultMethod):
    OBJECT_TYPE = "GroupBy"

    @classmethod
    def register(cls, func: Union[str, Callable], **kw: Any) -> Callable:
        fn_name = func if isinstance(func, str) else getattr(func, "__name__", str(func))

        def caller(
            query_compiler: Any,
            by: Any,
            agg_args: tuple = (),
            agg_kwargs: Optional[dict] = None,
            groupby_kwargs: Optional[dict] = None,
            drop: bool = False,
            **kwargs: Any,
        ) -> Any:
            from modin_tpu.utils import try_cast_to_pandas

            df = query_compiler.to_pandas()
            by = try_cast_to_pandas(by, squeeze=True)
            groupby_kwargs = dict(groupby_kwargs or {})
            agg_kwargs = agg_kwargs or {}
            ErrorMessage.default_to_pandas(f"`GroupBy.{fn_name}`")
            grp = df.groupby(by=by, **groupby_kwargs)
            if callable(func):
                result = func(grp, *agg_args, **agg_kwargs)
            else:
                result = getattr(grp, fn_name)(*agg_args, **agg_kwargs)
            return cls.build_output(query_compiler, result)

        caller.__name__ = f"groupby_{fn_name}"
        return caller


class BinaryDefault(DefaultMethod):
    """Defaults for binary operations: aligns the ``other`` QC to pandas first."""

    @classmethod
    def register(cls, func: Union[str, Callable], squeeze_self: bool = False, **kw: Any) -> Callable:
        fn = cls.get_func(func, pandas.DataFrame)
        # lookup name (resolves the Series counterpart method) stays tied to
        # the pandas callable; fn_name only overrides the display/QC name
        lookup_name = (
            func if isinstance(func, str) else getattr(func, "__name__", str(func))
        )
        fn_name = kw.get("fn_name") or lookup_name

        def caller(
            query_compiler: Any, other: Any, *args: Any, **kwargs: Any
        ) -> Any:
            from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL, try_cast_to_pandas

            squeeze_other = kwargs.pop("squeeze_other", False)
            df = query_compiler.to_pandas()
            do_squeeze = squeeze_self or query_compiler._shape_hint == "column"
            if do_squeeze:
                df = df.squeeze(axis=1)
                if isinstance(df, pandas.Series) and df.name == MODIN_UNNAMED_SERIES_LABEL:
                    df.name = None
                if kwargs.get("axis") in ("columns", 1):
                    kwargs["axis"] = 0
            other = try_cast_to_pandas(other)
            if isinstance(other, pandas.DataFrame) and squeeze_other:
                other = other.squeeze(axis=1)
            ErrorMessage.default_to_pandas(f"`{fn_name}`")
            if fn_name.startswith("__"):
                # dunder binary ops take only `other`; the API layer's
                # axis/level hints don't apply (Series dunders align by index)
                kwargs = {
                    k: v for k, v in kwargs.items()
                    if k not in ("axis", "level", "fill_value")
                }
            if isinstance(df, pandas.Series):
                series_fn = getattr(pandas.Series, lookup_name, None)
                result = (
                    series_fn(df, other, *args, **kwargs)
                    if series_fn is not None
                    else fn(df, other, *args, **kwargs)
                )
            else:
                result = fn(df, other, *args, **kwargs)
            return cls.build_output(query_compiler, result)

        caller.__name__ = fn_name
        caller._pandas_signature_default = True
        return caller
