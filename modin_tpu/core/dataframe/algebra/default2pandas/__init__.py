"""Default-to-pandas builders (reference: modin/core/dataframe/algebra/default2pandas/)."""

from modin_tpu.core.dataframe.algebra.default2pandas.default import (  # noqa: F401
    BinaryDefault,
    CatDefault,
    DataFrameDefault,
    DateTimeDefault,
    DefaultMethod,
    EwmDefault,
    ExpandingDefault,
    GroupByDefault,
    ListDefault,
    ResampleDefault,
    RollingDefault,
    SeriesDefault,
    StrDefault,
    StructDefault,
)
