"""modin_tpu subpackage."""
