"""modin_tpu subpackage."""
