"""Adaptive query progress (AQP): live progress for API-layer operations.

Reference design: modin/core/execution/modin_aqp.py:32 — a tqdm bar tracking
outstanding partition futures per line of user code.  On the device engine
there is one fused computation instead of N partition tasks, so progress is
reported per operation: a bar appears for calls that outlive a threshold and
completes when the device result is ready.  Gated by the ProgressBar config.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

_LONG_OP_SECONDS = 0.5
_reentrancy = threading.local()


class _OpProgress:
    """Displays a spinner/bar for one long-running operation."""

    def __init__(self, name: str):
        self.name = name
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._span_stack: Any = None
        self._scopes: Any = None

    def __enter__(self) -> "_OpProgress":
        from modin_tpu.observability import meters as graftmeter
        from modin_tpu.observability import spans as graftscope

        _reentrancy.active = True
        self._span_stack = graftscope.snapshot_stack()
        self._scopes = graftmeter.snapshot_scopes()
        self._thread = threading.Thread(
            target=self._run,
            name=f"modin-tpu-progress-{self.name}",
            daemon=True,
        )
        self._thread.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        _reentrancy.active = False
        self._done.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def _run(self) -> None:
        from modin_tpu.observability import meters as graftmeter
        from modin_tpu.observability import spans as graftscope

        # the spinner reports on the caller's operation: adopt its
        # span/QueryStats context so anything it emits bills the owner
        graftscope.seed_thread(self._span_stack)
        graftmeter.seed_thread_scopes(self._scopes)
        try:
            # wait before showing anything: short ops stay silent
            if self._done.wait(_LONG_OP_SECONDS):
                return
            try:
                from tqdm.auto import tqdm

                bar = tqdm(
                    desc=f"modin_tpu::{self.name}", total=None, leave=False
                )
                while not self._done.wait(0.25):
                    bar.update(1)
                bar.close()
            except ImportError:
                start = time.time()
                while not self._done.wait(1.0):
                    elapsed = time.time() - start
                    print(  # noqa: T201
                        f"\rmodin_tpu::{self.name} running {elapsed:.0f}s",
                        end="",
                    )
                print("\r", end="")  # noqa: T201
        finally:
            graftmeter.seed_thread_scopes(None)
            graftscope.seed_thread(None)


def call_progress_bar(name: str) -> Any:
    """Context manager showing progress for ``name`` when ProgressBar is on.

    Only the OUTERMOST API call gets a bar: nested API-layer calls inside an
    active operation are no-ops (re-entrancy guard).
    """
    import contextlib

    from modin_tpu.config import ProgressBar

    if not ProgressBar.get() or getattr(_reentrancy, "active", False):
        return contextlib.nullcontext()
    return _OpProgress(name)
