"""modin_tpu subpackage."""
