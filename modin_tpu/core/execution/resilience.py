"""Fault-tolerant device execution: taxonomy, retry, and circuit breakers.

The engine contract (``JaxWrapper.deploy/put/materialize/wait``,
modin_tpu/parallel/engine.py) is the single seam between the framework and
the accelerator runtime.  Everything that can go wrong on the other side of
that seam — device OOM, a wedged TPU tunnel, a transient XLA runtime error —
used to surface as a raw ``XlaRuntimeError`` that either crashed the query or
was swallowed by a broad ``except Exception`` and misread as a semantic
"not supported on device" fallback.  This module makes the failure mode a
first-class, observable runtime decision (the design argued for by
"Towards Scalable Dataframe Systems", arXiv:2001.00888, and the adaptive
per-operator routing of Xorbits, arXiv:2401.00865):

1. **Failure taxonomy** — ``classify_device_error`` maps low-level runtime
   errors onto ``DeviceOOM`` (RESOURCE_EXHAUSTED), ``DeviceLost`` (tunnel /
   device failure, including watchdog expiry), and ``TransientDeviceError``
   (everything retryable).  These are *infrastructure* failures, disjoint
   from the semantic fallback signals (``ShuffleSkewError``,
   ``_TooManyGroups``, ``ModinAssumptionError``) which mean "the optimized
   path does not apply", not "the device is unhealthy".

2. **Bounded retry with exponential backoff** — ``engine_call`` wraps every
   engine-seam invocation; transient errors are retried up to
   ``ResilienceRetries`` times with ``ResilienceBackoffS`` exponential
   backoff.  ``materialize``/``wait`` additionally run under a wall-clock
   watchdog (``ResilienceWatchdogS``): a fetch that outlives it raises
   ``WatchdogTimeout`` (a ``DeviceLost``) instead of hanging the query
   forever on a dead tunnel.

3. **Per-device-path circuit breaker** — every ``_try_*`` family in the
   TPU query compiler is wrapped by ``device_path(family)``.  Each family
   owns a named breaker that counts device failures and latency-budget
   violations; after ``ResilienceBreakerThreshold`` consecutive strikes the
   breaker trips OPEN and the family short-circuits to the pandas fallback
   without touching the device.  After ``ResilienceBreakerCooldownS`` it
   lets one HALF_OPEN probe through; a clean probe closes the breaker, a
   failed probe re-opens it.  A wedged tunnel or pathologically slow kernel
   therefore degrades the *path*, never the *answer*.

All state transitions, retries, and fallbacks are published through
``emit_metric`` (modin_tpu/logging/metrics.py) as
``modin_tpu.resilience.*`` counters.  The deterministic fault-injection
harness lives in modin_tpu/testing/faults.py; knobs are the
``MODIN_TPU_RESILIENCE_*`` parameters in modin_tpu/config/envvars.py.
"""

from __future__ import annotations

import functools
import queue
import re
import threading
import time
from typing import Any, Callable, Dict, Optional

from modin_tpu.concurrency import named_lock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import meters as graftmeter
from modin_tpu.observability import spans as graftscope
from modin_tpu.observability.flight_recorder import dump_flight_record

# graftgate serving context (deadline tokens + degraded routing).  A leaf
# module by design — serving/__init__ loads only errors+context eagerly —
# so this import cannot cycle; every seam check below gates on the single
# module attribute serving_context.CONTEXT_ON (False unless a serving
# query scope or ad-hoc deadline is active anywhere in the process).
from modin_tpu.serving import context as serving_context

# test seams: the suite patches these to run breaker-cooldown / backoff
# scenarios without wall-clock sleeps
_now = time.monotonic
_sleep = time.sleep

# fault-injection seam: modin_tpu.testing.faults installs a callable here;
# it runs inside every engine-seam attempt (under the watchdog, before the
# real work) so injected faults traverse the same classify/retry/breaker
# machinery a real device failure would
_fault_hook: Optional[Callable[[str], None]] = None


# ---------------------------------------------------------------------- #
# 1. Failure taxonomy
# ---------------------------------------------------------------------- #


class DeviceFailure(RuntimeError):
    """Base for classified infrastructure failures at the engine seam.

    Disjoint from the semantic fallback signals (ShuffleSkewError,
    _TooManyGroups, ModinAssumptionError): a DeviceFailure means the device
    runtime misbehaved, not that the optimized path declined the inputs.
    """

    kind = "device_failure"


class DeviceOOM(DeviceFailure):
    """Device memory exhausted (XLA RESOURCE_EXHAUSTED).  Not retried: the
    same program over the same buffers will exhaust the same HBM."""

    kind = "oom"


class DeviceLost(DeviceFailure):
    """The device or its transport is gone (tunnel drop, device reset).
    Not retried: recovery needs the breaker cooldown, not a tight loop.

    ``shard_index`` is the mesh row shard the runtime named in the error
    (parsed from a ``shard_index=N`` message fragment), or None when the
    loss is unattributed.  graftmesh recovery uses it to re-seat ONLY that
    shard's slice of each column instead of rebuilding whole columns.
    """

    kind = "device_lost"
    shard_index: Optional[int] = None


class WatchdogTimeout(DeviceLost):
    """A materialize/wait outlived the configured wall-clock watchdog.
    Treated as DeviceLost: a fetch that never returns is a dead transport."""

    kind = "watchdog_timeout"


class TransientDeviceError(DeviceFailure):
    """A retryable runtime hiccup (DEADLINE_EXCEEDED, ABORTED, INTERNAL...)."""

    kind = "transient"


# message fragments -> classification, checked in order (first match wins).
# XLA surfaces absl status codes in the message text; the tunnel transport
# adds socket/connection wording of its own.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OOM", "Out of memory")
_LOST_MARKERS = (
    "DEVICE_LOST",
    "device lost",
    "UNAVAILABLE",
    "socket closed",
    "connection reset",
    "connection refused",
    "tunnel",
    "heartbeat",
    "NOT_FOUND: device",
)
_RUNTIME_ERROR_TYPE_NAMES = ("XlaRuntimeError", "JaxRuntimeError")
#: graftfuse: dispatching over a buffer a previous donated dispatch consumed
#: surfaces as a plain ValueError/RuntimeError, not an XlaRuntimeError — the
#: engine's own retry of a donated thunk on real hardware hits exactly this.
#: Classified as DeviceLost so the deploy rebind leg rebuilds the argument
#: tree from the (lineage-restorable) columns and dispatches over live
#: buffers, instead of crashing the query on a retry artifact.
_DONATED_MARKERS = ("deleted or donated", "Array has been deleted")

#: a runtime error message may name the lost shard (the fault harness does;
#: real runtimes name devices in their own formats, unparsed = None)
_SHARD_INDEX_RE = re.compile(r"shard_index=(\d+)")


def is_device_runtime_error(exc: BaseException) -> bool:
    """True if ``exc`` is the accelerator runtime's error type (by name, so
    the check works against any jaxlib version and the fault harness's
    stand-in without importing either)."""
    return any(
        t.__name__ in _RUNTIME_ERROR_TYPE_NAMES for t in type(exc).__mro__
    )


def classify_device_error(exc: BaseException) -> Optional[DeviceFailure]:
    """Map ``exc`` onto the taxonomy, or None if it is not a device failure.

    None means the exception is the caller's problem (a semantic signal or a
    genuine bug) and must propagate — classification never swallows it.
    """
    if isinstance(exc, DeviceFailure):
        return exc
    if not is_device_runtime_error(exc):
        if isinstance(exc, (ValueError, RuntimeError)) and any(
            m in str(exc) for m in _DONATED_MARKERS
        ):
            return DeviceLost(str(exc))
        return None
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return DeviceOOM(msg)
    if any(m in msg for m in _LOST_MARKERS):
        failure = DeviceLost(msg)
        shard = _SHARD_INDEX_RE.search(msg)
        if shard is not None:
            failure.shard_index = int(shard.group(1))
        return failure
    # unknown runtime error: assume transient so it gets a bounded retry and
    # then strikes the breaker rather than crashing the query
    return TransientDeviceError(msg)


# ---------------------------------------------------------------------- #
# 2. Engine-seam wrapper: retry with backoff + watchdog
# ---------------------------------------------------------------------- #


def _run_with_watchdog(op: str, thunk: Callable[[], Any], timeout_s: float) -> Any:
    """Run ``thunk`` bounded by ``timeout_s`` wall-clock seconds.

    A daemon thread (NOT ThreadPoolExecutor: its atexit hook would join a
    wedged worker and hang interpreter shutdown — same rationale as the
    device probe in modin_tpu/utils/show_versions) does the blocking call;
    expiry raises WatchdogTimeout and abandons the thread.
    """
    result_q: "queue.Queue" = queue.Queue()
    # propagate span context onto the worker: spans/compile-attribution in
    # the thunk nest under the caller's call chain instead of floating
    # parentless
    parent_stack = graftscope.snapshot_stack() if graftscope.TRACE_ON else None
    # same for query-stats scopes: compile events observed inside the thunk
    # emit on THIS worker thread, and the owning query's rollup must see
    # them (QueryStats routing is lock-guarded and terminal at scope close,
    # so a worker abandoned by a watchdog timeout can race the owner's
    # retry — or outlive the scope — without corrupting the rollup)
    parent_scopes = (
        graftmeter.snapshot_scopes() if graftmeter.ACCOUNTING_ON else None
    )
    # and the serving context: a deadline must bound work the worker does
    # on the owner's behalf (nested engine calls inside the thunk)
    parent_ctx = (
        serving_context.snapshot_context()
        if serving_context.CONTEXT_ON
        else None
    )

    def runner() -> None:
        if parent_stack is not None:
            graftscope.seed_thread(parent_stack)
        if parent_scopes is not None:
            graftmeter.seed_thread_scopes(parent_scopes)
        if parent_ctx is not None:
            serving_context.seed_thread_context(parent_ctx)
        try:
            result_q.put((True, thunk()))
        except BaseException as err:  # noqa: BLE001 - relayed to caller  # graftlint: disable=EXC-HYGIENE -- watchdog thread relays ANY exception to the waiting caller verbatim
            result_q.put((False, err))

    thread = threading.Thread(
        target=runner, daemon=True, name=f"modin-tpu-watchdog-{op}"
    )
    thread.start()
    # a query deadline tighter than the watchdog bounds the wait instead:
    # the blocking fetch is abandoned the moment the budget is gone, and
    # the expiry surfaces as the TYPED serving error — not as a
    # WatchdogTimeout, which would misread a slow-but-healthy device as
    # lost and trigger a pointless lineage re-seat.  The wait loops so a
    # deadline-clamped get that wakes *before* the watchdog window closes
    # (deadline not quite expired, value not quite ready) keeps waiting
    # instead of misclassifying.
    started = time.monotonic()  # real clock: tests patch _now for breakers
    while True:
        wait_s = timeout_s - (time.monotonic() - started)
        if wait_s <= 0:
            emit_metric(f"resilience.watchdog.{op}.timeout", 1)
            raise WatchdogTimeout(
                f"{op} exceeded the {timeout_s:g}s resilience watchdog "
                "(MODIN_TPU_RESILIENCE_WATCHDOG_S); treating the device "
                "path as lost"
            ) from None
        if serving_context.CONTEXT_ON:
            # raises DeadlineExceeded when the budget expired; abandoning
            # the daemon worker is the same trade the watchdog already
            # makes for a wedged fetch
            serving_context.check_deadline(f"engine.{op}.watchdog")
            remaining = serving_context.remaining_s()
            if remaining is not None:
                wait_s = min(wait_s, max(remaining, 1e-3))
        try:
            ok, payload = result_q.get(timeout=wait_s)
            break
        except queue.Empty:
            continue
    if ok:
        return payload
    raise payload


def _run_attempt(op: str, attempt_once: Callable[[], Any], timeout_s: float) -> Any:
    """One attempt, under the watchdog when requested and — while a serving
    context is active — under the collective-safe dispatch lock for the
    program-enqueue ops (see serving/context.py:dispatch_lock: concurrent
    sharded enqueues that interleave per-device deadlock the collective
    rendezvous).

    The watchdog branch comes FIRST and is never serialized: blocking
    fetches only drain results, and the lock must never span a worker
    handoff — an owner holding it while a daemon worker enqueues would
    release on abandonment (timeout/deadline) with the enqueue still in
    flight, recreating the interleave the lock exists to prevent, and a
    nested deploy on the worker would stall against its own owner.  If a
    program-enqueue op ever grows a watchdog, take the lock INSIDE the
    worker, not here.
    """
    if timeout_s > 0:
        return _run_with_watchdog(op, attempt_once, timeout_s)
    if serving_context.CONTEXT_ON and op in ("deploy", "put"):
        with serving_context.dispatch_lock:
            return attempt_once()
    return attempt_once()


def engine_call(
    op: str,
    thunk: Callable[[], Any],
    watchdog: bool = False,
    protect_ids: Optional[set] = None,
    cost_cb: Optional[Callable[[bool, Any, float], None]] = None,
) -> Any:
    """Run one engine-seam invocation under the resilience policy.

    Transient failures retry up to ``ResilienceRetries`` times with
    exponential backoff.  ``watchdog=True`` (materialize/wait — the
    blocking fetches) additionally bounds each attempt by
    ``ResilienceWatchdogS``.

    graftguard (core/execution/recovery.py) upgrades the two formerly
    terminal failure kinds:

    - ``DeviceOOM`` gets up to ``SpillRetries`` **evict-then-retry**
      rounds — spill the coldest device columns to host (never the ones
      in ``protect_ids``: the failing op's own inputs, pinned by the
      thunk closure), then re-dispatch — before the OOM is terminal;
    - ``DeviceLost`` gets one **lineage re-seat**: every live device
      column is rebuilt from its provenance on the (fresh) device and
      the call retried.  The retry re-runs the SAME thunk — its closure
      still references the pre-loss buffers, which an injected fault
      leaves intact but a real loss kills; ``JaxWrapper.deploy`` adds the
      rebind-and-redispatch leg for that case, and the pandas fallbacks
      read the re-seated/host data either way.

    Both legs are skipped while a recovery pass is itself on the stack
    (no recursive recovery) and when ``MODIN_TPU_RECOVERY_MODE=Disable``.

    ``cost_cb`` (graftcost, deploy only) runs on the dispatching thread
    after a successful attempt with ``(compiled, attempt_span,
    attempt_wall_s)`` — while the ``engine.<op>.attempt`` span is still
    open, so static cost attributes land on the span that did the work,
    and with the wall of the successful attempt alone (retries/backoff
    excluded).  It is pre-guarded (never raises) and only passed while
    ``costs.COST_ON``.
    """
    from modin_tpu.config import (
        ResilienceBackoffS,
        ResilienceMode,
        ResilienceRetries,
        ResilienceWatchdogS,
        SpillRetries,
    )
    from modin_tpu.core.execution import recovery

    # graftgate deadline: one seam check before any engine work, covering
    # the ResilienceMode=Disable bypass too — a budget-expired query must
    # not enqueue more device work in either mode
    if serving_context.CONTEXT_ON:
        serving_context.check_deadline(f"engine.{op}")

    def attempt_once() -> Any:
        hook = _fault_hook
        if hook is not None:
            hook(op)
        return thunk()

    if ResilienceMode.get() == "Disable":
        compiles_before = None
        if op == "deploy" and cost_cb is not None:
            from modin_tpu.observability.compile_ledger import (
                compiles_on_this_thread,
            )

            compiles_before = compiles_on_this_thread()
        attempt_t0 = time.perf_counter()
        result = _run_attempt(op, attempt_once, 0.0)
        attempt_wall = time.perf_counter() - attempt_t0
        # accounting still owes the dispatch count under the bypass knob —
        # EXPLAIN ANALYZE / the metrics_smoke ceilings must not go blind
        # just because resilience is off
        if op == "deploy" and graftmeter.ACCOUNTING_ON:
            graftmeter.note_dispatch()
        if compiles_before is not None:
            from modin_tpu.observability.compile_ledger import (
                compiles_on_this_thread,
            )

            cost_cb(
                compiles_on_this_thread() > compiles_before, None, attempt_wall
            )
        return result

    timeout_s = float(ResilienceWatchdogS.get()) if watchdog else 0.0
    retries = int(ResilienceRetries.get())
    backoff_s = float(ResilienceBackoffS.get())
    spill_retries = int(SpillRetries.get())
    attempt = 0
    oom_rounds = 0
    reseat_spent = False
    while True:
        if serving_context.CONTEXT_ON:
            # attempt-start boundary: a retry / evict-then-retry / re-seat
            # loop re-enters here, so deadline overshoot is bounded by ONE
            # attempt, never by the remaining retry budget
            serving_context.check_deadline(f"engine.{op}.attempt")
        sp = compiles_before = None
        if graftscope.TRACE_ON:
            sp = graftscope.start_span(
                f"engine.{op}.attempt",
                layer="JAX-ENGINE",
                attrs={"op": op, "attempt": attempt},
            )
        if op == "deploy" and (sp is not None or cost_cb is not None):
            from modin_tpu.observability.compile_ledger import (
                compiles_on_this_thread,
            )

            compiles_before = compiles_on_this_thread()
        # the epoch this attempt's work launches in: a DeviceLost below
        # hands it to reseat_all so concurrent observers of ONE loss share
        # one recovery pass (reseat-once) instead of re-seating per thread
        attempt_epoch = recovery.current_epoch()
        attempt_t0 = time.perf_counter()
        try:
            result = _run_attempt(op, attempt_once, timeout_s)
        except Exception as err:  # graftlint: disable=EXC-HYGIENE -- the classification point: catches broadly, re-raises non-device errors
            failure = classify_device_error(err)
            if sp is not None:
                sp.attrs["failure_kind"] = (
                    failure.kind if failure is not None else type(err).__name__
                )
                graftscope.finish_span(sp, status="error")
            if failure is None:
                raise
            emit_metric(f"resilience.engine.{op}.{failure.kind}", 1)
            if (
                isinstance(failure, DeviceOOM)
                and oom_rounds < spill_retries
                and not recovery.in_recovery()
                and recovery.evict_for_oom(op, exclude_ids=protect_ids) > 0
            ):
                # evict-then-retry: cold columns were spilled to host, so
                # the same dispatch now has the HBM it asked for
                oom_rounds += 1
                emit_metric("recovery.retry.oom", 1)
                continue
            if (
                isinstance(failure, DeviceLost)
                and not reseat_spent
                and not recovery.in_recovery()
                and recovery.reseat_all(
                    f"engine_{op}",
                    observed_epoch=attempt_epoch,
                    shard_index=getattr(failure, "shard_index", None),
                )
                > 0
            ):
                # lineage re-seat: resident columns were rebuilt on the
                # fresh device; give the call one post-recovery retry
                reseat_spent = True
                emit_metric("recovery.retry.device_lost", 1)
                continue
            if not isinstance(failure, TransientDeviceError) or attempt >= retries:
                # terminal for this call: preserve the trace that led here
                if dump_flight_record(f"terminal_{failure.kind}", detail=op):
                    emit_metric("trace.flight_dump", 1)
                raise failure from err
            attempt += 1
            emit_metric(f"resilience.engine.{op}.retry", 1)
            delay_s = backoff_s * (2 ** (attempt - 1))
            if serving_context.CONTEXT_ON:
                # a backoff sleep never outlives the query's budget: sleep
                # at most the remaining time, and the attempt-start check
                # above turns the expiry into the typed abort
                delay_s = serving_context.clamp_sleep(delay_s)
            _sleep(delay_s)
            continue
        except BaseException:  # graftlint: disable=EXC-HYGIENE -- span-stack unwind only (KeyboardInterrupt, bench SIGALRM); re-raised immediately
            # a non-Exception unwind (Ctrl-C, SectionTimeout) must still pop
            # the attempt span or every later span on this thread parents
            # under a stale entry
            if sp is not None:
                graftscope.finish_span(sp, status="error")
            raise
        if compiles_before is not None:
            from modin_tpu.observability.compile_ledger import (
                compiles_on_this_thread,
                get_compile_ledger,
            )

            compiled = compiles_on_this_thread() > compiles_before
            if sp is not None:
                get_compile_ledger().record_dispatch(
                    graftscope.attribution_signature(), compiled=compiled
                )
            if cost_cb is not None:
                # the SUCCESSFUL attempt's wall: failed attempts and the
                # backoff sleeps between them are never billed as dispatch
                cost_cb(compiled, sp, time.perf_counter() - attempt_t0)
        if op == "deploy" and graftmeter.ACCOUNTING_ON:
            graftmeter.note_dispatch()
        if sp is not None:
            graftscope.finish_span(sp)
        return result


# ---------------------------------------------------------------------- #
# 3. Per-device-path circuit breaker
# ---------------------------------------------------------------------- #

#: Every breaker family a ``@device_path`` decorator in the TPU query
#: compiler may use.  This is the operator-facing catalog: docs, dashboards,
#: and ``breaker_snapshot`` consumers key off these names, and graftlint's
#: FALLBACK-PARITY rule cross-checks it both ways (an undeclared family in
#: the compiler, or a declared family with no ``_try_*`` user, is drift).
#: Tests may still create ad-hoc families (e.g. "probe_unit") at runtime;
#: only the query compiler's production paths are held to the registry.
DEVICE_PATH_FAMILIES = frozenset(
    {
        "binary",
        "reduce",
        "dt_component",
        "str_lut",
        "top_k",
        "corr_cov",
        "shift",
        "merge",
        "rolling",
        "ewm",
        "resample",
        "expanding",
        "groupby",
        "shuffle_apply",
        "sort_shuffle",
        # graftsort: the sort-shaped reduction family (median / quantile /
        # nunique / mode) behind the kernel router (ops/router.py)
        "sort_reduce",
    }
)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Consecutive-strike breaker guarding one named device path.

    CLOSED: calls flow; every device failure or latency-budget violation is
    a strike, every clean call resets the count.  ``threshold`` strikes trip
    it OPEN: calls short-circuit to the fallback for ``cooldown_s`` seconds.
    Then one HALF_OPEN probe is admitted — success closes, failure re-opens
    (with a fresh cooldown).  Thresholds are read from config at trip-check
    time so tests and operators can retune a live process.
    """

    def __init__(self, name: str):
        self.name = name
        self.state = CLOSED
        self.strikes = 0
        self.opened_at = 0.0
        self._lock = named_lock("resilience.breaker")

    # -- config ------------------------------------------------------- #

    @staticmethod
    def _threshold() -> int:
        from modin_tpu.config import ResilienceBreakerThreshold

        return int(ResilienceBreakerThreshold.get())

    @staticmethod
    def _cooldown_s() -> float:
        from modin_tpu.config import ResilienceBreakerCooldownS

        return float(ResilienceBreakerCooldownS.get())

    def _transition(self, state: str) -> bool:
        """Record the state change; returns True when it opened (the caller
        dumps the flight record AFTER releasing the breaker lock — disk IO
        under the lock would stall every thread short-circuiting on it)."""
        self.state = state
        emit_metric(f"resilience.breaker.{self.name}.{state}", 1)
        return state == OPEN

    def _dump_open(self) -> None:
        """Flight-record a trip to OPEN: the spans that led up to the
        degradation (no-op unless tracing is on; rate-limited; never
        raises).  Must be called WITHOUT the breaker lock held."""
        if dump_flight_record(f"breaker_open_{self.name}"):
            emit_metric("trace.flight_dump", 1)

    # -- protocol ------------------------------------------------------ #

    def allow(self) -> bool:
        """May the guarded path run right now?"""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if _now() - self.opened_at >= self._cooldown_s():
                    self._transition(HALF_OPEN)
                    return True
                return False
            # HALF_OPEN: one probe is already in flight this cooldown; hold
            # further calls on the fallback until it reports
            return False

    def record_success(self, latency_s: float = 0.0) -> None:
        from modin_tpu.config import ResilienceLatencyBudgetS

        budget = float(ResilienceLatencyBudgetS.get())
        if budget > 0 and latency_s > budget:
            emit_metric(f"resilience.breaker.{self.name}.slow", 1)
            self._strike()
            return
        with self._lock:
            self.strikes = 0
            if self.state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        self._strike()

    def abort_probe(self) -> None:
        """The in-flight HALF_OPEN probe ended without a health verdict
        (an unclassified exception escaped).  Return to OPEN with a fresh
        cooldown — staying HALF_OPEN would short-circuit the family forever,
        since only a probe can leave that state."""
        opened = False
        with self._lock:
            if self.state == HALF_OPEN:
                self.opened_at = _now()
                opened = self._transition(OPEN)
        if opened:
            self._dump_open()

    def _strike(self) -> None:
        opened = False
        with self._lock:
            self.strikes += 1
            emit_metric(f"resilience.breaker.{self.name}.strike", 1)
            if self.state == HALF_OPEN:
                # failed probe: straight back to OPEN, fresh cooldown
                self.opened_at = _now()
                opened = self._transition(OPEN)
            elif self.state == CLOSED and self.strikes >= self._threshold():
                self.opened_at = _now()
                opened = self._transition(OPEN)
        if opened:
            self._dump_open()


_BREAKERS: Dict[str, CircuitBreaker] = {}
_breakers_lock = named_lock("resilience.breakers")


def get_breaker(name: str) -> CircuitBreaker:
    with _breakers_lock:
        breaker = _BREAKERS.get(name)
        if breaker is None:
            breaker = _BREAKERS[name] = CircuitBreaker(name)
        return breaker


def breaker_snapshot() -> Dict[str, str]:
    """{family: state} for introspection / debugging."""
    with _breakers_lock:
        return {name: b.state for name, b in _BREAKERS.items()}


def reset_breakers() -> None:
    """Forget all breaker state (tests; operator escape hatch)."""
    with _breakers_lock:
        _BREAKERS.clear()


def drop_breaker(name: str) -> None:
    """Forget one breaker by name (graftgate's tenant registry evicts idle
    tenants' health breakers so per-user tenant ids cannot grow this
    registry without bound; device-path families are never dropped)."""
    with _breakers_lock:
        _BREAKERS.pop(name, None)


def device_path(family: str) -> Callable:
    """Decorator for ``TpuQueryCompiler._try_*`` methods: per-family breaker.

    The wrapped method keeps its contract — return a result, or None for
    "use the pandas fallback".  The wrapper adds the infrastructure leg:

    - breaker OPEN  -> return None immediately (short-circuit, no device
      contact) and count it;
    - a classified DeviceFailure raised anywhere inside the call -> strike
      the breaker, count the fallback, return None (the caller's pandas
      default produces the answer);
    - anything unclassified (semantic signals handled inside the method,
      genuine bugs) propagates untouched;
    - a clean call reports its latency so budget violations strike too.
    """

    def decorator(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(self: Any, *args: Any, **kwargs: Any) -> Any:
            from modin_tpu.config import ResilienceMode

            if ResilienceMode.get() == "Disable":
                return fn(self, *args, **kwargs)
            if serving_context.CONTEXT_ON and serving_context.degraded_active():
                # graftgate degraded mode: this thread's query was admitted
                # while the device was sick (open breaker / ledger past
                # high water) — route it to the pandas fallback exactly
                # like an open breaker would, without touching the device
                emit_metric("serving.degraded.fallback", 1)
                if graftscope.TRACE_ON:
                    graftscope.finish_span(
                        graftscope.start_span(
                            f"fallback.{family}",
                            layer="QUERY-COMPILER",
                            attrs={"family": family, "reason": "degraded"},
                        )
                    )
                return None
            breaker = get_breaker(family)
            if not breaker.allow():
                emit_metric(f"resilience.breaker.{family}.short_circuit", 1)
                if graftscope.TRACE_ON:
                    graftscope.finish_span(
                        graftscope.start_span(
                            f"fallback.{family}",
                            layer="QUERY-COMPILER",
                            attrs={"family": family, "reason": "short_circuit"},
                        )
                    )
                return None
            start = _now()
            try:
                if serving_context.CONTEXT_ON:
                    # collective-safe dispatch (serving/context.py): the
                    # kernel families direct-call their jitted programs, so
                    # the whole guarded device path serializes — two
                    # threads' sharded programs reaching the per-device
                    # queues in different orders deadlock the collective
                    # rendezvous.  Host/pandas fallbacks stay concurrent.
                    with serving_context.dispatch_lock:
                        result = fn(self, *args, **kwargs)
                else:
                    result = fn(self, *args, **kwargs)
            except Exception as err:  # graftlint: disable=EXC-HYGIENE -- device_path classification point: unclassified exceptions propagate
                failure = classify_device_error(err)
                if failure is None:
                    # not the device's fault — but if this call was the
                    # HALF_OPEN probe, the breaker must not wait forever for
                    # a verdict that will never come: re-open it so the next
                    # cooldown admits a fresh probe
                    breaker.abort_probe()
                    raise
                breaker.record_failure()
                if isinstance(failure, DeviceLost) and breaker.state == OPEN:
                    # terminal breaker-open on a lost device: re-seat the
                    # resident columns from lineage NOW so the pandas
                    # fallbacks this family degrades to (and every other
                    # family) read healthy buffers instead of poisoned ones
                    from modin_tpu.core.execution import recovery

                    if not recovery.in_recovery():
                        recovery.reseat_all(
                            f"breaker_open_{family}",
                            shard_index=getattr(
                                failure, "shard_index", None
                            ),
                        )
                emit_metric(f"resilience.fallback.{family}.{failure.kind}", 1)
                if graftscope.TRACE_ON:
                    graftscope.finish_span(
                        graftscope.start_span(
                            f"fallback.{family}",
                            layer="QUERY-COMPILER",
                            attrs={"family": family, "reason": failure.kind},
                        )
                    )
                return None
            breaker.record_success(_now() - start)
            return result

        wrapper._resilience_family = family
        return wrapper

    return decorator
