"""IO bound to the TPU (sharded jax.Array) storage format on the JAX engine.

Reference composition pattern: ray/implementations/pandas_on_ray/io/io.py:81-99
builds per-format reader classes by mixing (EngineWrapper, Parser, Dispatcher);
here the engine wrapper is the jax device layer and the dispatchers bind the
Tpu query compiler directly.
"""

from typing import Any

from modin_tpu.core.dataframe.tpu.dataframe import TpuDataframe
from modin_tpu.core.io.column_stores.hdf_dispatcher import HDFDispatcher
from modin_tpu.core.io.column_stores.parquet_dispatcher import (
    FeatherDispatcher,
    ParquetDispatcher,
)
from modin_tpu.core.io.io import BaseIO
from modin_tpu.core.io.sql.sql_dispatcher import SQLDispatcher
from modin_tpu.core.io.text.csv_dispatcher import CSVDispatcher, TableDispatcher
from modin_tpu.core.io.text.fwf_dispatcher import FWFDispatcher
from modin_tpu.core.io.text.json_dispatcher import JSONDispatcher
from modin_tpu.core.storage_formats.tpu.query_compiler import TpuQueryCompiler


class TpuCSVDispatcher(CSVDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuTableDispatcher(TableDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuJSONDispatcher(JSONDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuFWFDispatcher(FWFDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuParquetDispatcher(ParquetDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuFeatherDispatcher(FeatherDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuHDFDispatcher(HDFDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuSQLDispatcher(SQLDispatcher):
    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe


class TpuOnJaxIO(BaseIO):
    """IO producing device-backed TpuQueryCompiler frames.

    read_csv/read_table/read_parquet go through parallel dispatchers (native
    byte-range chunking / pyarrow row groups); everything else through host
    pandas then ``device_put``.
    """

    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe

    @classmethod
    def read_csv(cls, **kwargs: Any):
        # graftplan: a deferrable read becomes a Scan-rooted plan; the file
        # is parsed at the first materialization point, with any projection
        # the rewrite rules pushed down merged into the reader kwargs
        from modin_tpu.plan import runtime as graftplan

        deferred = graftplan.defer_read(TpuCSVDispatcher, kwargs)
        if deferred is not None:
            return deferred
        return TpuCSVDispatcher.read(**kwargs)

    @classmethod
    def read_table(cls, **kwargs: Any):
        from modin_tpu.plan import runtime as graftplan

        deferred = graftplan.defer_read(TpuTableDispatcher, kwargs)
        if deferred is not None:
            return deferred
        return TpuTableDispatcher.read(**kwargs)

    @classmethod
    def read_json(cls, **kwargs: Any):
        return TpuJSONDispatcher.read(**kwargs)

    @classmethod
    def read_fwf(cls, **kwargs: Any):
        return TpuFWFDispatcher.read(**kwargs)

    @classmethod
    def read_parquet(cls, **kwargs: Any):
        return TpuParquetDispatcher.read(**kwargs)

    @classmethod
    def read_feather(cls, **kwargs: Any):
        return TpuFeatherDispatcher.read(**kwargs)

    @classmethod
    def read_hdf(cls, **kwargs: Any):
        return TpuHDFDispatcher.read(**kwargs)

    @classmethod
    def to_hdf(cls, qc: Any, path_or_buf: Any = None, **kwargs: Any):
        return TpuHDFDispatcher.write(qc, path_or_buf, **kwargs)

    @classmethod
    def read_sql(cls, **kwargs: Any):
        return TpuSQLDispatcher.read(**kwargs)

    @classmethod
    def to_sql(cls, qc: Any, **kwargs: Any):
        return TpuSQLDispatcher.write(qc, **kwargs)

    @classmethod
    def to_parquet(cls, qc: Any, path: Any = None, **kwargs: Any):
        # chunk-streamed writer: bounded host memory instead of a full gather
        # (reference: per-partition write, parquet_dispatcher.py:912)
        return TpuParquetDispatcher.write(qc, path, **kwargs)

    @classmethod
    def to_csv(cls, qc: Any, path_or_buf: Any = None, **kwargs: Any):
        return TpuCSVDispatcher.write(qc, path_or_buf, **kwargs)

    @classmethod
    def to_json(cls, qc: Any, path_or_buf: Any = None, **kwargs: Any):
        return TpuJSONDispatcher.write(qc, path_or_buf, **kwargs)

    @classmethod
    def to_feather(cls, qc: Any, path: Any = None, **kwargs: Any):
        return TpuFeatherDispatcher.write(qc, path, **kwargs)
