"""IO bound to the TPU (sharded jax.Array) storage format on the JAX engine."""

from modin_tpu.core.dataframe.tpu.dataframe import TpuDataframe
from modin_tpu.core.io.io import BaseIO
from modin_tpu.core.storage_formats.tpu.query_compiler import TpuQueryCompiler


class TpuOnJaxIO(BaseIO):
    """IO producing device-backed TpuQueryCompiler frames.

    read_csv/read_parquet get parallel host-parse + chunked device upload in
    the dedicated dispatchers (modin_tpu/core/io/); everything else goes
    through host pandas then ``device_put``.
    """

    query_compiler_cls = TpuQueryCompiler
    frame_cls = TpuDataframe
