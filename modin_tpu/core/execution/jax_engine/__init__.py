"""modin_tpu subpackage."""
