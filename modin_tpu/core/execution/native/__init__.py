"""Native (in-process pandas) execution."""
