"""IO bound to the in-process Native (plain pandas) backend."""

from modin_tpu.core.io.io import BaseIO
from modin_tpu.core.storage_formats.native.query_compiler import NativeQueryCompiler


class NativeIO(BaseIO):
    """Serial pandas IO producing NativeQueryCompiler frames."""

    query_compiler_cls = NativeQueryCompiler
    frame_cls = None
