"""modin_tpu subpackage."""
