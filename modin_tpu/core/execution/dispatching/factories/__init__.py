"""modin_tpu subpackage."""
