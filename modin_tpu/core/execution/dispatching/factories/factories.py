"""Execution factories: map (StorageFormat, Engine) -> bound IO class.

Reference design: /root/reference/modin/core/execution/dispatching/factories/factories.py:133-567.
"""

from __future__ import annotations

import re
import typing
from typing import Any, NamedTuple

from modin_tpu.core.execution.utils import Execution
from modin_tpu.core.io.io import BaseIO
from modin_tpu.utils import get_current_execution


class FactoryInfo(NamedTuple):
    """Structured info about a factory: engine name, partition format, experimental flag."""

    engine: str
    partition: str
    experimental: bool


class NotRealFactory(Exception):
    pass


class BaseFactory:
    """Base class of all execution factories."""

    io_cls: typing.Type[BaseIO] = None

    @classmethod
    def get_info(cls) -> FactoryInfo:
        try:
            experimental = "Experimental" in cls.__name__
            partition, engine = re.match(
                r"^(?:Experimental)?(.*)On(.*)Factory$", cls.__name__
            ).groups()
        except AttributeError:
            raise NotRealFactory()
        return FactoryInfo(engine=engine, partition=partition, experimental=experimental)

    @classmethod
    def prepare(cls) -> None:
        """Initialize the factory: import and bind the IO class."""
        raise NotImplementedError(
            f"{cls.__name__} is intended to be used without instantiation"
        )

    # -- IO dispatch: every method forwards to the bound io_cls -------- #

    @classmethod
    def _from_pandas(cls, df):
        return cls.io_cls.from_pandas(df)

    @classmethod
    def _from_arrow(cls, at):
        return cls.io_cls.from_arrow(at)

    @classmethod
    def _from_non_pandas(cls, *args: Any, **kwargs: Any):
        return cls.io_cls.from_non_pandas(*args, **kwargs)

    @classmethod
    def _from_interchange_dataframe(cls, df):
        return cls.io_cls.from_interchange_dataframe(df)

    @classmethod
    def _from_map(cls, func, iterable, *args: Any, **kwargs: Any):
        return cls.io_cls.from_map(func, iterable, *args, **kwargs)


def _make_io_forwarder(name: str):
    @classmethod
    def forwarder(cls, **kwargs: Any):
        return getattr(cls.io_cls, name)(**kwargs)

    forwarder.__func__.__name__ = f"_{name}"
    return forwarder


for _name in (
    "read_parquet", "read_csv", "read_pickle", "read_table", "read_fwf",
    "read_clipboard", "read_excel", "read_hdf", "read_feather", "read_stata",
    "read_sas", "read_html", "read_sql", "read_sql_query", "read_sql_table",
    "read_json", "read_xml", "read_spss", "read_orc",
):
    setattr(BaseFactory, f"_{_name}", _make_io_forwarder(_name))


def _make_writer_forwarder(name: str):
    @classmethod
    def forwarder(cls, qc, **kwargs: Any):
        return getattr(cls.io_cls, name)(qc, **kwargs)

    forwarder.__func__.__name__ = f"_{name}"
    return forwarder


for _name in (
    "to_csv", "to_parquet", "to_json", "to_xml", "to_excel", "to_hdf",
    "to_feather", "to_stata", "to_pickle", "to_sql", "to_orc",
):
    setattr(BaseFactory, f"_{_name}", _make_writer_forwarder(_name))


class TpuOnJaxFactory(BaseFactory):
    """The flagship execution: sharded jax.Array storage on the JAX/XLA engine."""

    @classmethod
    def prepare(cls) -> None:
        from modin_tpu.core.execution.jax_engine.io import TpuOnJaxIO

        cls.io_cls = TpuOnJaxIO


class PandasOnPythonFactory(BaseFactory):
    """Serial in-process block-partitioned execution (debugging/tests)."""

    @classmethod
    def prepare(cls) -> None:
        from modin_tpu.core.execution.python_engine.io import PandasOnPythonIO

        cls.io_cls = PandasOnPythonIO


class NativeOnNativeFactory(BaseFactory):
    """Plain in-process pandas, no partitioning at all."""

    @classmethod
    def prepare(cls) -> None:
        from modin_tpu.core.execution.native.io import NativeIO

        cls.io_cls = NativeIO


class StubIoEngine:
    """IO-class stand-in raising informative errors for unknown engines."""

    def __init__(self, factory_name: str = ""):
        self.factory_name = factory_name or "Unknown"

    def __getattr__(self, name: str):
        factory_name = self.factory_name

        def stub(*args: Any, **kw: Any):
            raise NotImplementedError(
                f"Method {factory_name}.{name} is not implemented"
            )

        return stub


class StubFactory(BaseFactory):
    """Factory that does nothing more than raise NotImplementedError when called."""

    io_cls = StubIoEngine()

    @classmethod
    def set_failing_name(cls, factory_name: str) -> "type[StubFactory]":
        cls.io_cls = StubIoEngine(factory_name)
        return cls
