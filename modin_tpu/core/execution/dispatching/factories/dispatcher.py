"""``FactoryDispatcher`` — singleton routing IO/from_* calls to the current factory.

Reference design: /root/reference/modin/core/execution/dispatching/factories/dispatcher.py:104.
Subscribes to ``Engine``/``StorageFormat``/``Backend`` config changes and
re-binds the active factory, lazily initializing the engine on first touch.
"""

from __future__ import annotations

from typing import Any

from modin_tpu.config import Backend, Engine, StorageFormat
from modin_tpu.core.execution.dispatching.factories import factories
from modin_tpu.core.execution.utils import Execution
from modin_tpu.error_message import ErrorMessage
from modin_tpu.utils import get_current_execution


class FactoryNotFoundError(AttributeError):
    pass


class FactoryDispatcher(object):
    """Routes calls to the factory for the active (StorageFormat, Engine)."""

    __factory: type = None
    _initialized_engines: set = set()

    @classmethod
    def get_factory(cls) -> type:
        if cls.__factory is None:
            from modin_tpu.pandas import _initialize_engine

            Engine.subscribe(_initialize_engine)
            Engine.subscribe(cls._update_factory)
            StorageFormat.subscribe(cls._update_factory)
        return cls.__factory

    @classmethod
    def _update_factory(cls, *args: Any) -> None:
        factory_name = get_current_execution() + "Factory"
        experimental_factory_name = "Experimental" + factory_name
        try:
            cls.__factory = getattr(factories, factory_name, None) or getattr(
                factories, experimental_factory_name
            )
        except AttributeError:
            if not IsExperimental_ok():
                msg = (
                    f"Cannot find neither factory {factory_name} nor experimental "
                    f"factory {experimental_factory_name}. "
                    "Potential reason might be incorrect environment variable value for "
                    f"{StorageFormat.varname} or {Engine.varname}"
                )
                cls.__factory = factories.StubFactory.set_failing_name(factory_name)
                ErrorMessage.single_warning(msg)
                return
        try:
            cls.__factory.prepare()
        except ModuleNotFoundError as err:
            raise ModuleNotFoundError(
                f"Make sure all required packages are installed: {err}"
            ) from err

    @classmethod
    def get_backend_for_compiler(cls, qc_type: type) -> str:
        """Reverse-map a query-compiler class to its backend name."""
        from modin_tpu.core.storage_formats.native.query_compiler import (
            NativeQueryCompiler,
        )

        try:
            from modin_tpu.core.storage_formats.tpu.query_compiler import (
                TpuQueryCompiler,
            )

            if issubclass(qc_type, TpuQueryCompiler):
                return "Tpu"
        except ImportError:
            pass
        if issubclass(qc_type, NativeQueryCompiler):
            return "Pandas"
        return Backend.get()


def IsExperimental_ok() -> bool:
    return False


def _make_dispatch(name: str):
    @classmethod
    def dispatch(cls, *args: Any, **kwargs: Any):
        return getattr(cls.get_factory(), f"_{name}")(*args, **kwargs)

    dispatch.__func__.__name__ = name
    return dispatch


for _name in (
    "from_pandas", "from_arrow", "from_non_pandas", "from_interchange_dataframe",
    "from_map",
    "read_parquet", "read_csv", "read_pickle", "read_table", "read_fwf",
    "read_clipboard", "read_excel", "read_hdf", "read_feather", "read_stata",
    "read_sas", "read_html", "read_sql", "read_sql_query", "read_sql_table",
    "read_json", "read_xml", "read_spss", "read_orc",
    "to_csv", "to_parquet", "to_json", "to_xml", "to_excel", "to_hdf",
    "to_feather", "to_stata", "to_pickle", "to_sql", "to_orc",
):
    setattr(FactoryDispatcher, _name, _make_dispatch(_name))
