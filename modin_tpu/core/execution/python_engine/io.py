"""IO bound to the serial Python backend.

The Python engine exists for debugging and for unit-testing the stack without
devices (reference: modin/core/execution/python/).  It currently binds the
in-process pandas query compiler; the block-partitioned pandas storage format
replaces it when selected explicitly.
"""

from modin_tpu.core.io.io import BaseIO
from modin_tpu.core.storage_formats.native.query_compiler import NativeQueryCompiler


class PandasOnPythonIO(BaseIO):
    """Serial pandas IO for the Python debugging engine."""

    query_compiler_cls = NativeQueryCompiler
    frame_cls = None
