"""Serial Python execution engine (debugging/tests)."""
