"""Execution descriptor shared by config and factories."""

from typing import NamedTuple


class Execution(NamedTuple):
    """A (storage_format, engine) pair naming one execution backend."""

    storage_format: str
    engine: str
