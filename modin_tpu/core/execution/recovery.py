"""graftguard: lineage-based partition recovery for device columns.

The reference Modin delegates fault tolerance to its engine — Ray rebuilds a
lost object from the task lineage it recorded when the object was created
("Towards Scalable Dataframe Systems", arXiv:2001.00888, names fault
tolerance a core requirement the dataframe layer must inherit or provide).
Our JAX engine keeps no such substrate: before this module, a ``DeviceLost``
at the engine seam poisoned every ``DeviceColumn`` resident on the device —
the resilience layer (resilience.py) could only degrade the *current* op to
pandas, and every later op touching a dead buffer died too.

This module is the missing recovery substrate.  Every ``DeviceColumn``
carries a **lineage record** attached at creation time, one of three
provenance kinds:

- ``host`` (host-materialization) — the column's ``host_cache`` is an exact
  host copy; recovery is one ``JaxWrapper.put``.
- ``io`` (io-source) — the column came from a file read; the record holds
  the dispatcher + call args and re-reads the column on demand
  (modin_tpu/core/io/file_dispatcher.py attaches these).
- ``op`` (op-replay) — the column is the output of a device computation;
  the engine seam recorded the ``(func, args)`` of the ``deploy`` that
  produced it (weakly referencing the input buffers, so lineage never pins
  HBM), and recovery replays the op over recursively-recovered inputs.
  Replay depth is bounded by ``MODIN_TPU_LINEAGE_MAX_DEPTH``: a column
  whose chain would exceed it is **host-checkpointed at creation** (exact
  host copy fetched once, cutting the chain to depth 0).

On a ``DeviceLost`` (or a device-path breaker opening on one), the
recovery manager bumps the global **device epoch** — marking every resident
buffer suspect — and re-seats all live columns from their lineage on the
(fresh) device, so the in-flight engine call can be retried and the query
completes bit-exact instead of failing.  Everything is observable: the
``recovery.*`` metric families, a ``recovery.reseat`` span per pass, and a
flight-recorder dump tying the recovery to the spans that preceded it.

The companion *admission control* half of graftguard lives in
core/memory.py (``_DeviceLedger``) and parallel/engine.py (pre-flight
budget check at ``deploy``); the ``DeviceOOM`` evict-then-retry loop that
consumes :func:`evict_for_oom` is in resilience.py.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from modin_tpu.concurrency import named_lock, named_rlock
from modin_tpu.logging.metrics import emit_metric
from modin_tpu.observability import spans as graftscope
from modin_tpu.observability.flight_recorder import dump_flight_record

#: Lineage provenance kinds (short forms used in metric names):
#: ``host`` = host-materialization, ``io`` = io-source, ``op`` = op-replay,
#: ``opaque`` = adopted foreign buffer with no recorded provenance.
KIND_HOST = "host"
KIND_IO = "io"
KIND_OP = "op"
KIND_OPAQUE = "opaque"

#: module-level fast path, kept current by the RecoveryMode subscription —
#: instrumented hot paths (column registration, deploy provenance) check
#: this one attribute and pay nothing else while recovery is disabled
RECOVERY_ON: bool = True

_tls = threading.local()

_epoch_lock = named_lock("recovery.epoch")
_device_epoch = 0

#: serializes whole reseat passes AND carries the reseat-once handshake:
#: when several threads observe the same device loss (graftgate runs many
#: queries against one device), exactly one runs the recovery pass; the
#: others block on the lock, see the epoch already advanced past what they
#: observed, and piggyback on that pass's result instead of re-seating the
#: entire resident set once per observer.
_reseat_lock = named_lock("recovery.reseat")
_last_reseat_count = 0


class Unrecoverable(Exception):
    """A column's lineage cannot reproduce its device buffer (internal
    signal; never escapes the recovery manager)."""


class LineageRecord:
    """Provenance of one device column, attached at creation time.

    ``kind`` is one of the KIND_* constants; ``depth`` is the op-replay
    chain length below this column (0 for host/io/opaque); ``replay`` is
    the io-source re-read callable (returns the exact host values) and is
    None for every other kind; ``detail`` is a human-readable provenance
    note surfaced in debugging dumps (dispatcher name, op name).
    """

    __slots__ = ("kind", "depth", "replay", "detail")

    def __init__(
        self,
        kind: str,
        depth: int = 0,
        replay: Optional[Callable[[], Any]] = None,
        detail: str = "",
    ):
        self.kind = kind
        self.depth = depth
        self.replay = replay
        self.detail = detail

    def __repr__(self) -> str:
        return f"<LineageRecord {self.kind} depth={self.depth} {self.detail}>"


# ---------------------------------------------------------------------- #
# provenance capture at the engine seam
# ---------------------------------------------------------------------- #
#
# The engine wrapper (JaxWrapper.deploy/put) calls record_deploy /
# record_put after every successful dispatch.  Records are keyed by
# id(output array) with a weakref guard: the entry dies with the array
# (no pinning, no id-reuse hazard).  Input buffers inside a deploy record
# are held WEAKLY — lineage must never extend a buffer's lifetime, or the
# admission controller's spills would free nothing.


class _ArrRef:
    """Weak placeholder for a device-array leaf inside recorded args."""

    __slots__ = ("ref",)

    def __init__(self, arr: Any):
        self.ref = weakref.ref(arr)


class _DeployCall:
    """One recorded ``deploy`` invocation, shared by all its output leaves."""

    __slots__ = ("func", "args", "kwargs", "depth")

    def __init__(self, func: Callable, args: Any, kwargs: Optional[dict], depth: int):
        self.func = func
        self.args = args  # tree with array leaves replaced by _ArrRef
        self.kwargs = kwargs
        self.depth = depth


class _Record:
    """Provenance of one output array: how to replay it."""

    __slots__ = ("ref", "call", "path", "put_ref", "depth")

    def __init__(
        self,
        arr: Any,
        on_dead: Callable,
        call: Optional[_DeployCall] = None,
        path: Tuple[int, ...] = (),
        put_ref: Optional[weakref.ref] = None,
    ):
        self.ref = weakref.ref(arr, on_dead)
        self.call = call
        self.path = path
        self.put_ref = put_ref  # weakref to the host values given to put
        self.depth = call.depth if call is not None else 0


_prov_lock = named_rlock("recovery.provenance")
_provenance: Dict[int, _Record] = {}
#: id(device array) -> (weakref(owning DeviceColumn), weakref(the array));
#: lets op replay resolve an input buffer back to its column (and that
#: column's richer host/io lineage) instead of only the raw deploy chain.
#: The array weakref guards id reuse AND keeps the mapping valid after the
#: column re-seats onto a new buffer — which is exactly when a rebind
#: needs "old buffer -> same column, fresh buffer".
_columns_by_data: Dict[int, tuple] = {}


def _forget_record(key: int) -> None:
    with _prov_lock:
        _provenance.pop(key, None)


def _walk_leaves(tree: Any, path: Tuple[int, ...] = ()):
    """Yield (path, leaf) for array leaves in a (possibly nested) result."""
    if isinstance(tree, (tuple, list)):
        for i, item in enumerate(tree):
            yield from _walk_leaves(item, path + (i,))
    else:
        yield path, tree


def _is_device_array(x: Any) -> bool:
    from modin_tpu.parallel.engine import JaxWrapper

    return JaxWrapper.is_future(x)


def _encode_args(tree: Any) -> Any:
    """Recorded-args form of ``tree``: array leaves become weak _ArrRefs."""
    if isinstance(tree, (tuple, list)):
        return type(tree)(_encode_args(a) for a in tree)
    if _is_device_array(tree):
        return _ArrRef(tree)
    return tree


def _args_depth(tree: Any) -> int:
    """Max provenance depth over the array leaves of ``tree``.

    A leaf owned by a column defers to the column's lineage depth — a
    host-checkpointed column is depth 0 even though its raw deploy record
    remembers the full chain, which is exactly how a checkpoint restarts
    the chain below it.
    """
    depth = 0
    for _path, leaf in _walk_leaves(tree):
        if not _is_device_array(leaf):
            continue
        col = _lookup_column(leaf)
        lin = getattr(col, "lineage", None) if col is not None else None
        if lin is not None:
            depth = max(depth, lin.depth)
            continue
        rec = _lookup_record(leaf)
        if rec is not None and rec.call is not None:
            depth = max(depth, rec.depth)
    return depth


def _lookup_record(arr: Any) -> Optional[_Record]:
    with _prov_lock:
        rec = _provenance.get(id(arr))
    # identity check guards against id reuse racing the weakref callback
    return rec if rec is not None and rec.ref() is arr else None


def _lookup_column(arr: Any) -> Optional[Any]:
    with _prov_lock:
        entry = _columns_by_data.get(id(arr))
    if entry is None:
        return None
    col_ref, data_ref = entry
    if data_ref() is not arr:  # the keyed buffer died and its id was reused
        return None
    return col_ref()


def record_deploy(func: Callable, f_args: tuple, f_kwargs: Optional[dict], result: Any) -> None:
    """Record op-replay provenance for every array leaf of a deploy result."""
    if not RECOVERY_ON:
        return
    try:
        call = _DeployCall(
            func, _encode_args(f_args), f_kwargs, depth=1 + _args_depth(f_args)
        )
        with _prov_lock:
            for path, leaf in _walk_leaves(result):
                if not _is_device_array(leaf):
                    continue
                key = id(leaf)

                def _on_dead(_ref: Any, *, _key: int = key) -> None:
                    _forget_record(_key)

                _provenance[key] = _Record(leaf, _on_dead, call=call, path=path)
    except Exception:  # graftlint: disable=EXC-HYGIENE -- provenance capture is best-effort; a column without a record degrades to unrecoverable, never breaks the op
        pass


def record_put(host_values: Any, result: Any) -> None:
    """Record host-origin provenance for a ``put`` output (weak host ref)."""
    if not RECOVERY_ON:
        return
    try:
        if not _is_device_array(result):
            return
        key = id(result)

        def _on_dead(_ref: Any, *, _key: int = key) -> None:
            _forget_record(_key)

        with _prov_lock:
            _provenance[key] = _Record(
                result, _on_dead, put_ref=weakref.ref(host_values)
            )
    except Exception:  # graftlint: disable=EXC-HYGIENE -- provenance capture is best-effort (e.g. a non-weakrefable host buffer); recovery just has one fewer path
        pass


def note_column_data(col: Any) -> None:
    """Index ``col``'s concrete device buffer for input→column resolution."""
    data = col._data
    try:
        entry = (weakref.ref(col), weakref.ref(data))
    except TypeError:
        return  # not a weakref-able device buffer (deferred wrapper etc.)
    with _prov_lock:
        _columns_by_data[id(data)] = entry
        # bound the map: drop entries whose buffer or column died
        if len(_columns_by_data) > 4096:
            for k in [
                k
                for k, (col_ref, data_ref) in _columns_by_data.items()
                if data_ref() is None or col_ref() is None
            ]:
                _columns_by_data.pop(k, None)


# ---------------------------------------------------------------------- #
# lineage attachment (called by DeviceColumn at creation time)
# ---------------------------------------------------------------------- #


def current_epoch() -> int:
    return _device_epoch


def in_recovery() -> bool:
    return getattr(_tls, "active", False)


def attach_lineage(col: Any) -> None:
    """Attach the creation-time lineage record to ``col`` (and index its
    buffer).  Chains deeper than ``MODIN_TPU_LINEAGE_MAX_DEPTH`` are cut by
    an automatic host checkpoint: one exact host fetch now buys O(1)
    recovery later and keeps replay recursion bounded.
    """
    if not RECOVERY_ON:
        if col.lineage is None:
            col.lineage = LineageRecord(KIND_OPAQUE)
        return
    if col.lineage is not None:  # io-source records survive re-attachment
        return
    if col.host_cache is not None:
        col.lineage = LineageRecord(KIND_HOST)
        return
    rec = _lookup_record(col.raw)
    if rec is not None and rec.call is not None:
        from modin_tpu.config import LineageMaxDepth

        if rec.depth > int(LineageMaxDepth.get()):
            try:
                col.host_checkpoint()
                col.lineage = LineageRecord(
                    KIND_HOST, detail=f"checkpoint-cut@{rec.depth}"
                )
                emit_metric("recovery.checkpoint_cut", 1)
                return
            except Exception:  # graftlint: disable=EXC-HYGIENE -- checkpoint fetch is an optimization; on failure the deep op-replay chain remains the lineage
                pass
        col.lineage = LineageRecord(KIND_OP, depth=rec.depth)
        return
    if rec is not None and rec.put_ref is not None:
        col.lineage = LineageRecord(KIND_HOST, detail="put-origin")
        return
    col.lineage = LineageRecord(KIND_OPAQUE)


def attach_io_lineage(col: Any, replay: Callable[[], Any], detail: str) -> None:
    """Attach (or upgrade to) an io-source record: ``replay`` re-reads the
    column's exact host values from its file source on demand."""
    col.lineage = LineageRecord(KIND_IO, replay=replay, detail=detail)


# ---------------------------------------------------------------------- #
# recovery: re-seat columns from lineage
# ---------------------------------------------------------------------- #


def _replay_array(arr: Any, depth: int) -> Any:
    """A live device buffer equivalent to ``arr`` (recovered if possible).

    Resolution order: the owning column's lineage (host/io caches beat
    replay), then the raw deploy-provenance chain, then — with no lineage
    at all — the original reference (usable only if the runtime still
    honors it; a truly lost buffer will fail the replayed dispatch and the
    column counts as unrecoverable).
    """
    from modin_tpu.config import LineageMaxDepth

    if depth > int(LineageMaxDepth.get()):
        raise Unrecoverable(f"lineage deeper than LineageMaxDepth at {arr!r}")
    col = _lookup_column(arr)
    if col is not None:
        recover_column(col, depth=depth)
        fresh = col._data
        if fresh is not None and not getattr(col, "is_lazy", False):
            return fresh
    rec = _lookup_record(arr)
    if rec is not None:
        return _replay_record(rec, depth)
    return arr


def _replay_record(rec: _Record, depth: int) -> Any:
    from modin_tpu.parallel.engine import JaxWrapper

    if rec.put_ref is not None:
        host = rec.put_ref()
        if host is None:
            raise Unrecoverable("host origin of a put was garbage-collected")
        return JaxWrapper.put(host)
    call = rec.call
    if call is None:
        raise Unrecoverable("record has neither a put origin nor a deploy call")

    def _decode(tree: Any) -> Any:
        if isinstance(tree, (tuple, list)):
            return type(tree)(_decode(a) for a in tree)
        if isinstance(tree, _ArrRef):
            old = tree.ref()
            if old is None:
                raise Unrecoverable("an input buffer of the replay is gone")
            return _replay_array(old, depth + 1)
        return tree

    args = _decode(call.args)
    result = JaxWrapper.deploy(call.func, args, call.kwargs)
    for path, leaf in _walk_leaves(result):
        if path == rec.path:
            return leaf
    raise Unrecoverable("replayed op did not reproduce the output slot")


def recover_column(
    col: Any,
    depth: int = 0,
    force: bool = False,
    shard_index: Optional[int] = None,
) -> Optional[str]:
    """Re-seat one column's device buffer from its lineage.

    Returns the lineage kind used ("shard" for the graftmesh single-shard
    leg), or None when the column was already fresh (current epoch,
    concrete buffer).  Raises :class:`Unrecoverable` when no lineage can
    reproduce the buffer.

    ``shard_index`` (graftmesh): the loss named one mesh row shard — a
    column with an exact host copy re-uploads ONLY that shard's slice,
    keeping the surviving shards' buffers, instead of rebuilding the whole
    column (1/S of the transfer per column on an S-shard mesh).  Any
    failure of that leg falls through to the full paths below.
    """
    if getattr(col, "is_derived_cache", False):
        # graftsort sorted-representation rep (ops/sorted_cache.py): derived
        # data is disposable, never unrecoverable — drop it; the owning
        # column rebuilds it from its (recovered) buffer on next use
        col.drop()
        return None
    if getattr(col, "is_lazy", False):
        return None  # nothing device-resident to lose yet
    if col._data is None:
        # spilled: nothing device-resident was lost; the host copy restores
        # it on next access (and a spilled column always has one)
        return None
    if not force and col._device_epoch >= _device_epoch:
        return None
    if (
        shard_index is not None
        and col.host_cache is not None
        and col.reseat_from_host_shard(shard_index)
    ):
        return "shard"
    if col.host_cache is not None:
        col.reseat_from_host()
        return KIND_HOST
    lin = col.lineage
    if lin is not None and lin.kind == KIND_IO and lin.replay is not None:
        try:
            values = lin.replay()
        except Unrecoverable:
            raise
        except Exception as err:  # graftlint: disable=EXC-HYGIENE -- the io re-read hits filesystems/network; ANY failure means this lineage path is unusable, reported as Unrecoverable
            raise Unrecoverable(f"io-source replay failed: {err}") from err
        # the dead buffer goes first: while the re-read values are the sole
        # copy, is_spilled shields them from the host ledger's eviction
        col._data = None
        col.adopt_host_cache(values)
        col.reseat_from_host()
        return KIND_IO
    old = col._data
    rec = _lookup_record(old) if old is not None else None
    if rec is not None and (rec.call is not None or rec.put_ref is not None):
        fresh = _replay_record(rec, depth + 1)
        col.adopt_reseated(fresh)
        return KIND_OP
    raise Unrecoverable(
        f"no lineage for column dtype={col.pandas_dtype} len={col.length}"
    )


#: io-source replayers holding a per-epoch memo of their re-read values;
#: purged at the end of every recovery pass so one pass does not pin a
#: full host copy of the source dataset indefinitely
_io_replayers: "weakref.WeakSet" = weakref.WeakSet()


def note_io_replayer(replayer: Any) -> None:
    """Track ``replayer`` for end-of-pass cache purging."""
    _io_replayers.add(replayer)


def _purge_io_caches() -> None:
    for replayer in list(_io_replayers):
        try:
            replayer.drop_cache()
        except Exception:  # graftlint: disable=EXC-HYGIENE -- purge is best-effort housekeeping at the end of a recovery pass
            pass


def reseat_all(
    reason: str,
    observed_epoch: Optional[int] = None,
    shard_index: Optional[int] = None,
) -> int:
    """Bump the device epoch and re-seat every live device column.

    Called on a terminal ``DeviceLost`` at the engine seam and on a
    device-path breaker opening on one.  Returns how many columns were
    re-seated; 0 means nothing was resident (or recovery is disabled) and
    the caller should not bother retrying.

    ``shard_index`` (graftmesh): when the loss named one mesh row shard,
    columns with exact host copies replay only that shard's slice
    (``recovery.reseat.shard``) instead of re-uploading whole buffers —
    the pass then moves 1/S of the bytes a whole-column pass would.

    ``observed_epoch`` is the device epoch the caller's failed work was
    *launched* in (the engine seam captures it at attempt start).  It is
    the reseat-once handshake: when several threads observe the same
    device loss, the first to arrive runs the pass and bumps the epoch;
    every thread whose failure belongs to the already-recovered epoch
    piggybacks on that pass's result instead of churning the entire
    resident set (and dropping every derived cache) once per observer.
    """
    global _device_epoch, _last_reseat_count
    if not RECOVERY_ON or in_recovery():
        return 0
    from modin_tpu.core.memory import device_ledger

    if observed_epoch is None:
        observed_epoch = _device_epoch
    # Lock order: dispatch_lock -> _reseat_lock, ALWAYS.  A device-path
    # caller reaches here already holding the serving dispatch lock (the
    # guarded path wraps the whole kernel call), and the pass below replays
    # deploys that acquire it; taking it first here (reentrant for that
    # caller, a plain gate for everyone else) makes the order globally
    # consistent — without this, one thread holding dispatch wanting
    # reseat and another holding reseat wanting dispatch deadlock.
    from modin_tpu.serving import context as serving_context

    with serving_context.dispatch_lock, _reseat_lock:
        if _device_epoch > observed_epoch:
            return _last_reseat_count
        # the pass is SHARED work — every concurrent query's columns come
        # back through it — so it must not be abortable by the triggering
        # thread's private deadline: clear this thread's serving context
        # for the pass (restored below; the owner's scope bookkeeping is
        # untouched, only routing of seam checks)
        saved_ctx = serving_context.snapshot_context()
        if saved_ctx is not None:
            serving_context.seed_thread_context(None)
        _tls.active = True
        try:
            with _epoch_lock:
                _device_epoch += 1
            emit_metric("recovery.device_lost", 1)
            reseated = 0
            with graftscope.span(
                "recovery.reseat",
                layer="JAX-ENGINE",
                reason=reason,
                shard_index=-1 if shard_index is None else int(shard_index),
            ):
                for col in device_ledger.live_columns():
                    try:
                        # graftlint: disable=LOCK-BLOCKING -- re-deploying under dispatch/reseat is the point: the dispatch serialization exists so nothing else enqueues mid-recovery, and reseat must finish re-deploying before anyone dispatches
                        kind = recover_column(col, shard_index=shard_index)
                    except Unrecoverable:
                        emit_metric("recovery.unrecoverable", 1)
                        continue
                    except Exception:  # graftlint: disable=EXC-HYGIENE -- recovery is best-effort per column; one bad record must not abort the pass for every other column
                        emit_metric("recovery.unrecoverable", 1)
                        continue
                    if kind is not None:
                        emit_metric(f"recovery.reseat.{kind}", 1)
                        reseated += 1
            _last_reseat_count = reseated
            if dump_flight_record("recovery_reseat", detail=reason):
                emit_metric("trace.flight_dump", 1)
            return reseated
        finally:
            _tls.active = False
            if saved_ctx is not None:
                serving_context.seed_thread_context(saved_ctx)
            _purge_io_caches()


def recover_for_read(col: Any, err: BaseException) -> bool:
    """Last-chance read-path recovery for one column's host fetch.

    Called by ``DeviceColumn.to_numpy`` when its materialize raised through
    the engine seam's own recovery: if ``err`` classifies as a DeviceLost
    and the column has usable lineage, re-seat it and tell the caller to
    retry the fetch.  False means "nothing recovered — re-raise".
    """
    from modin_tpu.core.execution.resilience import (
        DeviceLost,
        classify_device_error,
    )

    if not RECOVERY_ON or in_recovery():
        return False
    if not isinstance(classify_device_error(err), DeviceLost):
        return False
    _tls.active = True
    try:
        try:
            kind = recover_column(col, force=True)
        except Unrecoverable:
            emit_metric("recovery.unrecoverable", 1)
            return False
        if kind is not None:
            emit_metric(f"recovery.reseat.{kind}", 1)
        return True
    finally:
        _tls.active = False
        _purge_io_caches()


def recover_args(tree: Any) -> Optional[Any]:
    """``tree`` with every device-array leaf swapped for its recovered
    incarnation, or None when nothing could be rebound.

    The engine-seam retry after a re-seat re-runs a thunk whose closure
    still references the OLD buffers; on a real device loss those are dead,
    so ``JaxWrapper.deploy`` uses this to rebuild its argument tree against
    the re-seated columns (or lineage replays) and dispatch once more over
    live buffers.
    """
    if not RECOVERY_ON or in_recovery():
        return None
    _tls.active = True
    try:

        def rebind(node: Any) -> Any:
            if isinstance(node, (tuple, list)):
                return type(node)(rebind(a) for a in node)
            if _is_device_array(node):
                return _replay_array(node, 0)
            return node

        try:
            return rebind(tree)
        except Unrecoverable:
            return None
    finally:
        _tls.active = False
        _purge_io_caches()


def evict_for_oom(op: str, exclude_ids: Any = None) -> int:
    """Spill cold device columns to make room after a ``DeviceOOM``.

    The evict-then-retry leg of resilience.py calls this before giving the
    failed dispatch another chance; returns the bytes freed (0 = nothing
    spillable, the caller should fall through to its existing handling).
    ``exclude_ids`` carries the ``id()`` of the failing op's own input
    buffers — spilling those frees nothing (the dispatch closure pins
    them), so they stay resident.
    """
    if not RECOVERY_ON or in_recovery():
        return 0
    from modin_tpu.config import SpillTargetFraction
    from modin_tpu.core.memory import device_ledger

    _tls.active = True
    try:
        resident = device_ledger.total_bytes()
        target = max(int(resident * float(SpillTargetFraction.get())), 1)
        return device_ledger.spill_lru(target, exclude_ids=exclude_ids)
    finally:
        _tls.active = False


# ---------------------------------------------------------------------- #
# dataset manifest (graftfleet warm-state recovery)
# ---------------------------------------------------------------------- #
#
# Lineage re-seats buffers inside ONE process; a dead replica process has
# no buffers left to re-seat.  The manifest is the process-level
# generalization of the io-source record: at dataset registration the
# serving layer records *how the dataset was read* (public reader name +
# call args, all picklable), and a respawned replica re-warms by replaying
# those reads through the public API — so the re-reads flow through
# ``FileDispatcher.read`` and io lineage, spans, and cost accounting see
# the replay exactly like the original read.

_manifest_lock = named_lock("recovery.manifest")
_dataset_manifest: Dict[str, dict] = {}


def register_dataset(
    name: str,
    reader: str,
    args: tuple = (),
    kwargs: Optional[dict] = None,
) -> None:
    """Record the re-read recipe for dataset ``name``.

    ``reader`` is a public ``modin_tpu.pandas`` reader name (``read_csv``,
    ``read_parquet``, ...); ``args``/``kwargs`` are its call arguments.
    The entry must pickle — it crosses the coordinator->replica socket —
    so unpicklable arguments are rejected here, at registration, not at
    respawn time when the dead replica needs it.
    """
    import pickle

    entry = {
        "name": str(name),
        "reader": str(reader),
        "args": tuple(args),
        "kwargs": dict(kwargs or {}),
    }
    try:
        pickle.dumps(entry)
    except Exception as err:  # graftlint: disable=EXC-HYGIENE -- nothing is swallowed: ANY pickling failure re-raises as a typed TypeError naming the dataset
        raise TypeError(
            f"dataset {name!r} manifest entry is not picklable: {err}"
        ) from err
    with _manifest_lock:
        _dataset_manifest[entry["name"]] = entry


def dataset_manifest() -> List[dict]:
    """Picklable snapshot of every registered dataset's re-read recipe."""
    with _manifest_lock:
        return [dict(entry) for entry in _dataset_manifest.values()]


def warm_from_manifest(entries: List[dict]) -> Dict[str, Any]:
    """Replay manifest ``entries`` through the public readers.

    Returns ``{name: frame}``.  Each replay also re-registers the entry
    locally, so the warmed process can itself hand the manifest onward.
    A reader that fails raises — a replica that cannot re-warm must not
    report ready and silently serve an empty dataset.
    """
    import modin_tpu.pandas as _pd

    frames: Dict[str, Any] = {}
    for entry in entries:
        reader = getattr(_pd, entry["reader"], None)
        if reader is None:
            raise ValueError(
                f"manifest names unknown reader {entry['reader']!r}"
            )
        frames[entry["name"]] = reader(*entry["args"], **entry["kwargs"])
        register_dataset(
            entry["name"], entry["reader"], entry["args"], entry["kwargs"]
        )
        emit_metric("fleet.warm.dataset", 1)
    return frames


# ---------------------------------------------------------------------- #
# config wiring & test seams
# ---------------------------------------------------------------------- #


def _on_recovery_param(param: Any) -> None:
    global RECOVERY_ON
    RECOVERY_ON = param.get() == "Enable"


def reset_for_tests() -> None:
    """Forget provenance and epoch state (test isolation)."""
    global _device_epoch, _last_reseat_count
    with _prov_lock:
        _provenance.clear()
        _columns_by_data.clear()
    with _epoch_lock:
        _device_epoch = 0
    _last_reseat_count = 0
    with _manifest_lock:
        _dataset_manifest.clear()


from modin_tpu.config import RecoveryMode as _RecoveryMode  # noqa: E402

_RecoveryMode.subscribe(_on_recovery_param)
