"""modin_tpu subpackage."""
