"""Per-method backend casting and cost-driven auto-switching.

Reference design: modin/core/storage_formats/pandas/query_compiler_caster.py
(:527 register, :925 the method wrapper, :598/:660 pre/post-op switch
points).  The reference wraps every public API method; here the wrap happens
one layer lower, on every public method of each concrete query compiler:

- **argument casting** (always on): a call whose arguments mix backends
  (a device frame merged with an in-process frame) routes every argument —
  including ``self`` — to the cheapest common backend, chosen by
  :class:`~.query_compiler_calculator.BackendCostCalculator` from the
  compilers' stay/move costs.  The TPU cost model makes this
  PCIe/tunnel-transfer aware: big device frames pull small host frames to
  the device, not the reverse.
- **pre-op auto-switch** (``AutoSwitchBackend`` config, default off): even
  single-backend calls compare the cost of staying against moving to each
  registered backend for this specific operation, and relocate when
  strictly cheaper — e.g. a small device frame about to run an operation
  with no device kernel (which would round-trip through host pandas anyway)
  moves to the Native backend once instead.

Wrapping happens in ``BaseQueryCompiler.__init_subclass__`` so any new
storage format participates automatically.
"""

from __future__ import annotations

import functools
import inspect
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

# concrete QC classes that can host data (filled by __init_subclass__)
_BACKEND_REGISTRY: List[type] = []

# methods that must never cast/switch: conversion+introspection machinery the
# caster itself relies on, and lifecycle hooks
_EXCLUDED = {
    "from_pandas", "to_pandas", "from_arrow", "to_numpy", "to_interchange",
    "from_interchange", "to_dataframe", "from_dataframe", "execute", "free",
    "finalize", "copy", "stay_cost", "move_to_cost", "move_to_me_cost",
    "default_to_pandas", "get_index", "get_columns", "get_axis_len",
    "get_backend", "set_backend", "qc_engine_switch_max_cost", "execute_on",
    "support_materialization_in_worker_process", "get_pandas_backend",
}


def register_backend_qc(cls: type) -> None:
    if cls not in _BACKEND_REGISTRY:
        _BACKEND_REGISTRY.append(cls)


def qc_class_for_backend(backend: str) -> type:
    """Resolve a backend name ("Tpu", "Pandas", ...) to its QC class."""
    from modin_tpu.core.execution.dispatching.factories.dispatcher import (
        FactoryDispatcher,
    )

    for cls in _BACKEND_REGISTRY:
        if FactoryDispatcher.get_backend_for_compiler(cls) == backend:
            return cls
    raise ValueError(f"No query compiler registered for backend {backend!r}")


def _iter_qcs(base_cls: type, args: tuple, kwargs: dict):
    for a in args:
        if isinstance(a, base_cls):
            yield a
        elif isinstance(a, (list, tuple)):
            for x in a:
                if isinstance(x, base_cls):
                    yield x
    for a in kwargs.values():
        if isinstance(a, base_cls):
            yield a
        elif isinstance(a, (list, tuple)):
            for x in a:
                if isinstance(x, base_cls):
                    yield x


def _cast_tree(value: Any, base_cls: type, target: type):
    if isinstance(value, base_cls):
        return value if type(value) is target else target.from_pandas(value.to_pandas())
    if isinstance(value, list):
        return [_cast_tree(v, base_cls, target) for v in value]
    if isinstance(value, tuple):
        return tuple(_cast_tree(v, base_cls, target) for v in value)
    return value


def _backend_costs(
    operation: str, compilers: List[Any], candidates: List[type]
) -> Dict[type, int]:
    """Aggregate stay+move cost of landing all compilers on each candidate."""
    from modin_tpu.core.storage_formats.base.query_compiler import QCCoercionCost

    totals: Dict[type, int] = {}
    for target in candidates:
        total = 0
        for qc in compilers:
            if type(qc) is target:
                cost = qc.stay_cost(None, operation, {})
                total += int(cost) if cost is not None else QCCoercionCost.COST_MEDIUM
            else:
                # both sides price the move: sender's transfer cost plus the
                # receiver's willingness (reference calculator aggregates both)
                cost = qc.move_to_cost(target, None, operation, {})
                total += int(cost) if cost is not None else QCCoercionCost.COST_MEDIUM
                me = target.move_to_me_cost(qc, None, operation, {})
                if me is not None:
                    total += int(me)
        totals[target] = total
    return totals


def _cheapest_backend(
    operation: str, compilers: List[Any], candidates: List[type]
) -> Optional[type]:
    totals = _backend_costs(operation, compilers, candidates)
    best, best_total = None, None
    for target in candidates:  # first candidate wins ties
        if best_total is None or totals[target] < best_total:
            best, best_total = target, totals[target]
    return best


# Explicit switch points (reference: query_compiler_caster.py:1222,1243
# register_function_for_post_op_switch / pre_op_switch): entries are
# (class_name or None, backend, method).  Pre-op points force backend
# consideration for a specific (backend, method) even while the global
# every-method auto-switch heuristic is off; post-op points re-price the
# RESULT after the op (ops known to shrink data hand small results to the
# in-process backend).
_PRE_OP_SWITCH_POINTS: set = set()
_POST_OP_SWITCH_POINTS: set = set()


def register_function_for_pre_op_switch(
    class_name: Optional[str] = None, backend: Optional[str] = None, method: str = ""
) -> None:
    _PRE_OP_SWITCH_POINTS.add((class_name, backend, method))


def register_function_for_post_op_switch(
    class_name: Optional[str] = None, backend: Optional[str] = None, method: str = ""
) -> None:
    _POST_OP_SWITCH_POINTS.add((class_name, backend, method))


def _is_switch_point(registry: set, backend: str, method: str) -> bool:
    return any(
        m == method and (b is None or b == backend) for (_c, b, m) in registry
    )


def _maybe_switch_result_backend(result: Any, name: str, self_type: type) -> Any:
    """Post-op backend switch: re-price the result and move it if strictly
    cheaper elsewhere (reference: _maybe_switch_backend_post_op :660)."""
    from modin_tpu.core.storage_formats.base.query_compiler import (
        BaseQueryCompiler,
    )

    if not isinstance(result, BaseQueryCompiler):
        return result
    result_type = type(result)
    candidates = [result_type] + [
        t for t in _BACKEND_REGISTRY if t is not result_type
    ]
    best = _cheapest_backend(name, [result], candidates)
    if best is not None and best is not result_type:
        moved = best.move_from(result)
        moved._shape_hint = result._shape_hint
        return moved
    return result


def _wrap_method(name: str, fn: Callable) -> Callable:
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        from modin_tpu.core.storage_formats.base.query_compiler import (
            BaseQueryCompiler,
        )

        self_type = type(self)
        others = [
            qc for qc in _iter_qcs(BaseQueryCompiler, args, kwargs)
        ]
        mixed = any(type(qc) is not self_type for qc in others)

        backend_name: Optional[str] = None
        if _PRE_OP_SWITCH_POINTS or _POST_OP_SWITCH_POINTS:
            from modin_tpu.core.execution.dispatching.factories.dispatcher import (
                FactoryDispatcher,
            )

            backend_name = FactoryDispatcher.get_backend_for_compiler(self_type)

        target: Optional[type] = None
        if mixed:
            candidates: List[type] = []
            for qc in [self, *others]:
                if type(qc) not in candidates:
                    candidates.append(type(qc))
            target = _cheapest_backend(name, [self, *others], candidates)
        else:
            from modin_tpu.config import AutoSwitchBackend

            consider = AutoSwitchBackend.get() or (
                backend_name is not None
                and _is_switch_point(_PRE_OP_SWITCH_POINTS, backend_name, name)
            )
            if consider and len(_BACKEND_REGISTRY) > 1:
                # self first: _cheapest_backend breaks ties toward the first
                # candidate, so staying put wins unless strictly cheaper
                candidates = [self_type] + [
                    t for t in _BACKEND_REGISTRY if t is not self_type
                ]
                best = _cheapest_backend(name, [self, *others], candidates)
                if best is not None and best is not self_type:
                    target = best

        if target is not None and (
            mixed or target is not self_type
        ):
            new_self = (
                self if self_type is target
                else target.from_pandas(self.to_pandas())
            )
            new_args = tuple(
                _cast_tree(a, BaseQueryCompiler, target) for a in args
            )
            new_kwargs = {
                k: _cast_tree(v, BaseQueryCompiler, target)
                for k, v in kwargs.items()
            }
            if self_type is target:
                result = fn(new_self, *new_args, **new_kwargs)
            else:
                result = getattr(new_self, name)(*new_args, **new_kwargs)
        else:
            result = fn(self, *args, **kwargs)

        if backend_name is not None and _is_switch_point(
            _POST_OP_SWITCH_POINTS, backend_name, name
        ):
            result = _maybe_switch_result_backend(result, name, self_type)
        return result

    wrapper.__qc_cast_wrapped__ = True
    return wrapper


def wrap_query_compiler_methods(cls: type) -> None:
    """Install casting wrappers over every public method of a concrete QC."""
    for name in dir(cls):
        if name.startswith("_") or name in _EXCLUDED:
            continue
        static = inspect.getattr_static(cls, name)
        if isinstance(static, (classmethod, staticmethod, property)):
            continue
        fn = getattr(cls, name, None)
        if not inspect.isfunction(fn):
            continue
        if getattr(fn, "__qc_cast_wrapped__", False):
            continue
        setattr(cls, name, _wrap_method(name, fn))
