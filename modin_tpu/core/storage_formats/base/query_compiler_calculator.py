"""``BackendCostCalculator`` — pick the cheapest common backend for an op.

Reference design: modin/core/storage_formats/base/query_compiler_calculator.py:76
— aggregate each argument's move/stay costs per candidate backend and choose
the minimum.  Used when an operation mixes query compilers from different
backends (e.g. a device frame + an in-process frame).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Type

from modin_tpu.core.storage_formats.base.query_compiler import (
    BaseQueryCompiler,
    QCCoercionCost,
)


class BackendCostCalculator:
    """Accumulates per-compiler costs and picks the cheapest target type."""

    def __init__(self, operation: str = "", api_cls_name: Optional[str] = None):
        self._operation = operation
        self._api_cls_name = api_cls_name
        self._compilers: List[BaseQueryCompiler] = []

    def add_query_compiler(self, qc: BaseQueryCompiler) -> None:
        self._compilers.append(qc)

    def calculate(self) -> Optional[Type[BaseQueryCompiler]]:
        """The compiler type every argument should be moved to (or None)."""
        if not self._compilers:
            return None
        # candidates in first-appearance order: ties keep the left operand's
        # backend (deterministic, avoids ping-ponging data)
        candidate_types: List[Type[BaseQueryCompiler]] = []
        for qc in self._compilers:
            if type(qc) not in candidate_types:
                candidate_types.append(type(qc))
        if len(candidate_types) == 1:
            return candidate_types[0]
        best: Optional[Type[BaseQueryCompiler]] = None
        best_total: Optional[int] = None
        for target in candidate_types:
            total = 0
            for qc in self._compilers:
                if type(qc) is target:
                    cost = qc.stay_cost(self._api_cls_name, self._operation, {})
                else:
                    cost = qc.move_to_cost(
                        target, self._api_cls_name, self._operation, {}
                    )
                total += int(cost) if cost is not None else QCCoercionCost.COST_MEDIUM
            if best_total is None or total < best_total:
                best, best_total = target, total
        return best


def coerce_to_common_backend(compilers: List[BaseQueryCompiler], operation: str = "") -> List[BaseQueryCompiler]:
    """Convert mixed-backend compilers to the cheapest common backend."""
    calculator = BackendCostCalculator(operation)
    for qc in compilers:
        calculator.add_query_compiler(qc)
    target = calculator.calculate()
    if target is None:
        return compilers
    return [
        qc if type(qc) is target else target.from_pandas(qc.to_pandas())
        for qc in compilers
    ]
