"""modin_tpu subpackage."""
