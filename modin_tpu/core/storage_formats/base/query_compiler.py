"""``BaseQueryCompiler`` — the abstract query-compiler every storage format implements.

Reference design: /root/reference/modin/core/storage_formats/base/query_compiler.py:162
(~460 methods, every one default-implemented by materializing to pandas).  The
TPU build keeps the same two-level strategy: this class is the correctness
floor (host pandas), and ``TpuQueryCompiler`` overrides the hot subset with
sharded jax.Array implementations.

A query compiler always represents a **2-D frame**; a Series is a one-column
frame whose ``_shape_hint`` is ``"column"`` (the API layer squeezes).
"""

from __future__ import annotations

import abc
from enum import IntEnum
from typing import Any, Callable, Hashable, List, Optional

import numpy as np
import pandas
from pandas._typing import IndexLabel
from pandas.core.dtypes.common import is_scalar

from modin_tpu.core.dataframe.algebra.default2pandas import (
    BinaryDefault,
    CatDefault,
    DataFrameDefault,
    DateTimeDefault,
    EwmDefault,
    ExpandingDefault,
    GroupByDefault,
    ListDefault,
    ResampleDefault,
    RollingDefault,
    SeriesDefault,
    StrDefault,
    StructDefault,
)
from modin_tpu.error_message import ErrorMessage
from modin_tpu.logging import ClassLogger
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL, try_cast_to_pandas


# ---------------------------------------------------------------------- #
# API-layer routing tables: public pandas method name -> named QC method.
# The API layer's fallback path (pandas/base.py:_default_to_pandas) consults
# these so the ENTIRE long tail dispatches through a *named* BaseQueryCompiler
# method — visible to the caster/cost model and overridable per backend —
# instead of short-circuiting to host pandas at the API layer (reference:
# every API method reaches one of base/query_compiler.py:162's ~460 methods).
# Only registrations whose QC signature is exactly the pandas signature are
# routed (they are generated from the pandas callable itself).
# ---------------------------------------------------------------------- #
DATAFRAME_QC_ROUTES: dict = {}
SERIES_QC_ROUTES: dict = {}


class QCCoercionCost(IntEnum):
    """Cost units for moving a frame between backends (reference: query_compiler.py:116)."""

    COST_ZERO = 0
    COST_LOW = 250
    COST_MEDIUM = 500
    COST_HIGH = 750
    COST_IMPOSSIBLE = 1000

    @classmethod
    def validate_coercion_cost(cls, cost: int) -> None:
        if int(cost) < cls.COST_ZERO or int(cost) > cls.COST_IMPOSSIBLE:
            raise ValueError("Query compiler coercion cost out of range")


def _set_axis(axis: int):
    def axis_setter(self: "BaseQueryCompiler", labels: pandas.Index) -> None:
        new_qc = DataFrameDefault.register(pandas.DataFrame.set_axis)(
            self, axis=axis, labels=labels
        )
        self.__dict__.update(new_qc.__dict__)

    return axis_setter


class BaseQueryCompiler(ClassLogger, abc.ABC, modin_layer="QUERY-COMPILER"):
    """Abstract interface between the API layer and a storage format."""

    _modin_frame: Any = None
    _shape_hint: Optional[str] = None

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        # every concrete storage format gets the per-method backend caster
        # (mixed-argument coercion + cost-driven auto-switch) and joins the
        # candidate-backend registry (reference: query_compiler_caster.py:527)
        from modin_tpu.core.storage_formats.base.query_compiler_caster import (
            register_backend_qc,
            wrap_query_compiler_methods,
        )

        wrap_query_compiler_methods(cls)
        register_backend_qc(cls)

    # --- lazy-evaluation capability flags (reference: query_compiler.py:259-303) ---
    lazy_row_labels = False
    lazy_row_count = False
    lazy_column_types = False
    lazy_column_labels = False

    @property
    def lazy_shape(self) -> bool:
        return self.lazy_row_count or self.lazy_column_labels

    @property
    def __constructor__(self) -> type:
        return type(self)

    # ------------------------------------------------------------------ #
    # Abstract data-exchange primitives
    # ------------------------------------------------------------------ #

    @classmethod
    @abc.abstractmethod
    def from_pandas(cls, df: pandas.DataFrame, data_cls: Any = None) -> "BaseQueryCompiler":
        """Build a QC from a pandas DataFrame."""

    @abc.abstractmethod
    def to_pandas(self) -> pandas.DataFrame:
        """Materialize to a pandas DataFrame."""

    @classmethod
    def from_arrow(cls, at: Any, data_cls: Any = None) -> "BaseQueryCompiler":
        return cls.from_pandas(at.to_pandas(), data_cls)

    def to_numpy(self, **kwargs: Any) -> np.ndarray:
        return self.to_pandas().to_numpy(**kwargs)

    def to_interchange_dataframe(self, nan_as_null: bool = False, allow_copy: bool = True):
        return self.to_pandas().__dataframe__(
            nan_as_null=nan_as_null, allow_copy=allow_copy
        )

    @classmethod
    def from_interchange_dataframe(cls, df: Any, data_cls: Any = None) -> "BaseQueryCompiler":
        from pandas.api.interchange import from_dataframe

        return cls.from_pandas(from_dataframe(df), data_cls)

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def copy(self) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.copy)(self)

    def free(self) -> None:
        """Release the underlying resources."""

    def finalize(self) -> None:
        """Finalize constructing the dataframe (flush deferred work)."""

    def execute(self) -> None:
        """Block until all submitted device/engine work for this frame completes."""

    def dispatch(self) -> None:
        """Enqueue any deferred work without blocking (no-op off-device)."""

    def support_materialization_in_worker_process(self) -> bool:
        return True

    # ------------------------------------------------------------------ #
    # Metadata
    # ------------------------------------------------------------------ #

    def get_index(self) -> pandas.Index:
        return self.to_pandas().index

    def get_columns(self) -> pandas.Index:
        return self.to_pandas().columns

    index = property(lambda self: self.get_index(), _set_axis(0))
    columns = property(lambda self: self.get_columns(), _set_axis(1))

    @property
    def dtypes(self) -> pandas.Series:
        return self.to_pandas().dtypes

    def get_dtypes_set(self) -> set:
        return set(self.dtypes.values)

    def get_axis_len(self, axis: int) -> int:
        return len(self.index if axis == 0 else self.columns)

    def is_series_like(self) -> bool:
        return len(self.columns) == 1 or len(self.index) == 1

    def set_index_name(self, name: Hashable, axis: int = 0) -> None:
        getattr(self, "index" if axis == 0 else "columns").name = name

    def get_index_name(self, axis: int = 0) -> Hashable:
        return getattr(self, "index" if axis == 0 else "columns").name

    def set_index_names(self, names: Any = None, axis: int = 0) -> None:
        getattr(self, "index" if axis == 0 else "columns").names = names

    def get_index_names(self, axis: int = 0) -> List[Hashable]:
        return getattr(self, "index" if axis == 0 else "columns").names

    def get_pandas_backend(self) -> Optional[str]:
        return None

    def repartition(self, axis: Optional[int] = None) -> "BaseQueryCompiler":
        return self

    # ------------------------------------------------------------------ #
    # Backend-movement cost model (reference: query_compiler.py:324-520)
    # ------------------------------------------------------------------ #

    def move_to_cost(self, other_qc_type: type, api_cls_name: Optional[str], operation: str, arguments: dict) -> Optional[int]:
        return None

    def stay_cost(self, api_cls_name: Optional[str], operation: str, arguments: dict) -> Optional[int]:
        return None

    @classmethod
    def move_to_me_cost(cls, other_qc: "BaseQueryCompiler", api_cls_name: Optional[str], operation: str, arguments: dict) -> Optional[int]:
        return None

    def max_cost(self) -> int:
        return QCCoercionCost.COST_IMPOSSIBLE

    def get_backend(self) -> str:
        from modin_tpu.core.execution.dispatching.factories.dispatcher import (
            FactoryDispatcher,
        )

        return FactoryDispatcher.get_backend_for_compiler(type(self))

    # ------------------------------------------------------------------ #
    # Generic defaulting
    # ------------------------------------------------------------------ #

    def default_to_pandas(self, pandas_op: Callable, *args: Any, **kwargs: Any) -> Any:
        """Materialize, apply ``pandas_op(df, *args, **kwargs)``, re-wrap."""
        op_name = getattr(pandas_op, "__name__", str(pandas_op))
        ErrorMessage.default_to_pandas(f"`{op_name}`")
        args = try_cast_to_pandas(args)
        kwargs = try_cast_to_pandas(kwargs)
        result = pandas_op(self.to_pandas(), *args, **kwargs)
        if isinstance(result, pandas.Series):
            if result.name is None:
                result = result.rename(MODIN_UNNAMED_SERIES_LABEL)
            result = result.to_frame()
        if isinstance(result, pandas.DataFrame):
            return self.from_pandas(result, type(self._modin_frame) if self._modin_frame is not None else None)
        return result

    # ------------------------------------------------------------------ #
    # Structural operations (explicit defaults; hot ones overridden by
    # concrete compilers)
    # ------------------------------------------------------------------ #

    def transpose(self, *args: Any, **kwargs: Any) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.transpose)(self)

    def columnarize(self) -> "BaseQueryCompiler":
        """Shape the frame into a single column (Series normal form)."""
        if len(self.columns) != 1 or (
            len(self.index) == 1 and self.index[0] == MODIN_UNNAMED_SERIES_LABEL
        ):
            result = self.transpose()
        else:
            # copy: the caller will tag/rename this as a Series; it must not
            # alias the parent frame's compiler
            result = self.copy()
        result._shape_hint = "column"
        return result

    def getitem_column_array(
        self, key: Any, numeric: bool = False, ignore_order: bool = False
    ) -> "BaseQueryCompiler":
        if numeric:
            return DataFrameDefault.register(
                lambda df, key: df.iloc[:, list(key)], fn_name="getitem_column_array"
            )(self, key=key)
        return DataFrameDefault.register(
            lambda df, key: df.loc[:, list(key)], fn_name="getitem_column_array"
        )(self, key=key)

    def getitem_row_array(self, key: Any) -> "BaseQueryCompiler":
        return DataFrameDefault.register(
            lambda df, key: df.iloc[list(key)], fn_name="getitem_row_array"
        )(self, key=key)

    def getitem_array(self, key: Any) -> "BaseQueryCompiler":
        if isinstance(key, type(self)):
            key = key.to_pandas().squeeze(axis=1)
        return DataFrameDefault.register(
            lambda df, key: df[key], fn_name="getitem_array"
        )(self, key=key)

    def take_2d_positional(
        self, index: Optional[Any] = None, columns: Optional[Any] = None
    ) -> "BaseQueryCompiler":
        index = slice(None) if index is None else index
        columns = slice(None) if columns is None else columns
        return DataFrameDefault.register(
            lambda df: df.iloc[index, columns], fn_name="take_2d_positional"
        )(self)

    def row_slice(self, start: Optional[int], stop: Optional[int], step: Optional[int] = None) -> "BaseQueryCompiler":
        """Positional row window — the repr/head/tail fast path."""
        return self.take_2d_positional(index=slice(start, stop, step))

    def insert(self, loc: int, column: Hashable, value: Any) -> "BaseQueryCompiler":
        value = try_cast_to_pandas(value, squeeze=True)

        def inserter(df: pandas.DataFrame) -> pandas.DataFrame:
            df = df.copy()
            df.insert(loc, column, value)
            return df

        return DataFrameDefault.register(inserter, fn_name="insert")(self)

    def insert_item(
        self, axis: int, loc: int, value: "BaseQueryCompiler", how: str = "inner", replace: bool = False
    ) -> "BaseQueryCompiler":
        assert isinstance(value, type(self)), "Cannot insert non-query-compiler values"
        delta = int(replace)
        if axis == 0:
            first = self.getitem_row_array(range(loc))
            second = self.getitem_row_array(range(loc + delta, self.get_axis_len(0)))
        else:
            first = self.getitem_column_array(range(loc), numeric=True)
            second = self.getitem_column_array(
                range(loc + delta, self.get_axis_len(1)), numeric=True
            )
        return first.concat(axis, [value, second], join=how, sort=False, ignore_index=False)

    def setitem(self, axis: int, key: Hashable, value: Any) -> "BaseQueryCompiler":
        value = try_cast_to_pandas(value, squeeze=True)

        def setitem(df: pandas.DataFrame, axis: int, key: Hashable, value: Any) -> pandas.DataFrame:
            df = df.copy()
            if is_scalar(key) and isinstance(value, pandas.DataFrame):
                value = value.squeeze(axis=1)
            if axis == 0:
                df[key] = value
            else:
                df.loc[key] = value
            return df

        return DataFrameDefault.register(setitem, fn_name="setitem")(
            self, axis=axis, key=key, value=value
        )

    def write_items(
        self, row_numeric_index: Any, col_numeric_index: Any, item: Any, need_columns_reindex: bool = True
    ) -> "BaseQueryCompiler":
        item = try_cast_to_pandas(item)

        def write_items_fn(df: pandas.DataFrame) -> pandas.DataFrame:
            df = df.copy()
            to_write = item
            if isinstance(to_write, (pandas.DataFrame, pandas.Series)):
                to_write = to_write.to_numpy() if not need_columns_reindex else to_write
            if isinstance(to_write, (pandas.DataFrame, pandas.Series)):
                to_write = np.asarray(to_write)
            if not is_scalar(to_write) and not isinstance(
                to_write, (pandas.DataFrame, pandas.Series)
            ):
                arr = np.asarray(to_write)
                if arr.ndim == 1:
                    n_rows_sel = (
                        len(range(*row_numeric_index.indices(len(df))))
                        if isinstance(row_numeric_index, slice)
                        else len(list(row_numeric_index))
                    )
                    n_cols_sel = (
                        len(range(*col_numeric_index.indices(df.shape[1])))
                        if isinstance(col_numeric_index, slice)
                        else len(list(col_numeric_index))
                    )
                    if n_cols_sel == 1 and len(arr) == n_rows_sel:
                        # a 1-D value into an (n, 1) selection is a column
                        # write, not a row broadcast
                        to_write = arr.reshape(-1, 1)
            df.iloc[
                list(row_numeric_index)
                if not isinstance(row_numeric_index, slice)
                else row_numeric_index,
                list(col_numeric_index)
                if not isinstance(col_numeric_index, slice)
                else col_numeric_index,
            ] = to_write
            return df

        return DataFrameDefault.register(write_items_fn, fn_name="write_items")(self)

    def drop(
        self,
        index: Optional[Any] = None,
        columns: Optional[Any] = None,
        errors: str = "raise",
    ) -> "BaseQueryCompiler":
        if index is None and columns is None:
            return self
        return DataFrameDefault.register(pandas.DataFrame.drop)(
            self, index=index, columns=columns, errors=errors
        )

    def concat(
        self,
        axis: int,
        other: Any,
        join: str = "outer",
        ignore_index: bool = False,
        sort: bool = False,
        **kwargs: Any,
    ) -> "BaseQueryCompiler":
        concat_join = "outer" if join != "inner" else "inner"

        def concat_fn(df: pandas.DataFrame, axis: int, other: Any, **kw: Any) -> pandas.DataFrame:
            ignore_index_kw = kw.pop("ignore_index", False)
            if isinstance(other, pandas.DataFrame):
                other = [other]
            return pandas.concat(
                [df] + other, axis=axis, join=concat_join, sort=sort,
                ignore_index=ignore_index_kw,
            )

        if not isinstance(other, (list, tuple)):
            other = [other]
        other = [o.to_pandas() if isinstance(o, BaseQueryCompiler) else o for o in other]
        result = DataFrameDefault.register(concat_fn, fn_name="concat")(
            self, axis=axis, other=other, ignore_index=ignore_index
        )
        if ignore_index:
            if axis == 0:
                return result.reset_index(drop=True)
            result.columns = pandas.RangeIndex(len(result.columns))
        return result

    def reindex(self, axis: int, labels: Any, **kwargs: Any) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.reindex)(
            self, axis=axis, labels=labels, **kwargs
        )

    def reset_index(self, **kwargs: Any) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.reset_index)(self, **kwargs)

    def set_index_from_columns(
        self, keys: List[Hashable], drop: bool = True, append: bool = False
    ) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.set_index)(
            self, keys=keys, drop=drop, append=append
        )

    def sort_rows_by_column_values(
        self, columns: Any, ascending: Any = True, **kwargs: Any
    ) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.sort_values)(
            self, by=columns, axis=0, ascending=ascending, **kwargs
        )

    def sort_columns_by_row_values(
        self, rows: Any, ascending: Any = True, **kwargs: Any
    ) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.sort_values)(
            self, by=rows, axis=1, ascending=ascending, **kwargs
        )

    def sort_index(self, **kwargs: Any) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.sort_index)(self, **kwargs)

    # ------------------------------------------------------------------ #
    # Reductions that need special squeezing/naming
    # ------------------------------------------------------------------ #

    # ------------------------------------------------------------------ #
    # Label -> position resolution (the loc/iloc seam; reference:
    # base/query_compiler.py:4844 get_positions_from_labels / :4809
    # take_2d_labels).  Implemented on axis metadata only — no data
    # materialization — so device frames stay on device through .loc.
    # ------------------------------------------------------------------ #

    def get_axis(self, axis: int) -> pandas.Index:
        return self.index if axis == 0 else self.columns

    def get_positions_from_labels(self, row_loc: Any, col_loc: Any) -> list:
        """Resolve loc-style row/column locators to iloc-style positions.

        Returns per axis: ``slice(None)`` for a full-axis grab (kept symbolic
        to avoid forcing lazy axis lengths), else a numpy position array or
        range-like.  Semantics follow pandas ``.loc`` exactly (reference
        base/query_compiler.py:4844): label slices are closed intervals;
        scalars resolve through ``Index.get_loc`` (partial-string datetime
        keys included); ``range``/``RangeIndex`` locators are *label lists*
        (missing labels raise ``KeyError``), not positions; MultiIndex axes
        resolve tuples through ``Index.get_locs`` (partial keys included) and
        label lists through level-0 selection.
        """
        from pandas.api.types import is_list_like

        out = []
        for axis, loc in ((0, row_loc), (1, col_loc)):
            if isinstance(loc, slice) and loc == slice(None):
                out.append(loc)
                continue
            if isinstance(loc, slice):
                lab = self.get_axis(axis)
                # label slices are closed intervals in .loc; slice_indexer
                # expects label bounds directly
                positions = lab.slice_indexer(loc.start, loc.stop, loc.step)
                n = len(lab)
                out.append(
                    pandas.RangeIndex(
                        positions.start + (n if positions.start < 0 else 0),
                        positions.stop + (n if positions.stop < 0 else 0),
                        positions.step,
                    )
                )
                continue
            if is_scalar(loc):
                out.append(self._scalar_label_positions(axis, loc))
                continue
            if isinstance(loc, tuple):
                if self.has_multiindex(axis):
                    # per-level selectors (partial or full key); get_locs
                    # raises KeyError for missing labels itself
                    lab = self.get_axis(axis)
                    out.append(np.asarray(lab.get_locs(list(loc))))
                else:
                    # on a flat index a tuple is itself a label
                    out.append(self._scalar_label_positions(axis, loc))
                continue
            if isinstance(loc, pandas.MultiIndex):
                lab = self.get_axis(axis)
                positions = lab.get_indexer_for(loc)
                if (positions == -1).any():
                    raise KeyError(list(loc[positions == -1]))
                out.append(np.asarray(positions))
                continue
            values = np.asarray(loc)
            if values.dtype == bool:
                lab = self.get_axis(axis)
                if len(values) != len(lab):
                    raise IndexError(
                        f"Boolean index has wrong length: "
                        f"{len(values)} instead of {len(lab)}"
                    )
                out.append(np.flatnonzero(values))
                continue
            lab = self.get_axis(axis)
            if self.has_multiindex(axis):
                keys = list(loc)
                if any(isinstance(k, tuple) for k in keys):
                    # list of (full) key tuples: exact-key selection
                    positions = lab.get_indexer_for(keys)
                    if (positions == -1).any():
                        raise KeyError(
                            [k for k, p in zip(keys, positions) if p == -1]
                        )
                    out.append(np.asarray(positions))
                else:
                    # list of scalars selects on the first level, keeping all
                    # levels (pandas .loc[list] on a MultiIndex)
                    out.append(np.asarray(lab.get_locs([keys])))
                continue
            if is_list_like(loc) and not isinstance(loc, (np.ndarray, pandas.Index)):
                try:
                    loc = np.asarray(list(loc), dtype=lab.dtype)
                except (TypeError, ValueError):
                    loc = np.asarray(list(loc), dtype=object)
            positions = lab.get_indexer_for(loc)
            missing = positions == -1
            if missing.any():
                raise KeyError(
                    f"{list(np.asarray(loc)[missing])} not in index"
                )
            out.append(positions)
        return out

    def _scalar_label_positions(self, axis: int, loc: Any) -> Any:
        """Positions of one scalar label via ``Index.get_loc`` (handles
        duplicate labels and partial-string datetime keys)."""
        lab = self.get_axis(axis)
        try:
            pos = lab.get_loc(loc)
        except TypeError:
            raise KeyError(loc)
        if isinstance(pos, slice):
            n = len(lab)
            return pandas.RangeIndex(
                (pos.start or 0) + (n if (pos.start or 0) < 0 else 0),
                pos.stop + (n if pos.stop < 0 else 0),
                pos.step or 1,
            )
        if isinstance(pos, np.ndarray):
            return np.flatnonzero(pos) if pos.dtype == bool else np.asarray(pos)
        return np.array([pos], dtype=np.int64)

    def take_2d_labels(self, index: Any, columns: Any) -> "BaseQueryCompiler":
        row_lookup, col_lookup = self.get_positions_from_labels(index, columns)
        return self.take_2d_positional(
            None if isinstance(row_lookup, slice) else row_lookup,
            None if isinstance(col_lookup, slice) else col_lookup,
        )

    def lookup(self, row_labels: Any, col_labels: Any) -> np.ndarray:
        """Label-pair fancy indexing (the removed ``DataFrame.lookup``)."""
        df = self.to_pandas()
        rows = df.index.get_indexer_for(row_labels)
        cols = df.columns.get_indexer_for(col_labels)
        return df.to_numpy()[rows, cols]

    def setitem_bool(self, row_loc: Any, col_loc: Any, item: Any) -> "BaseQueryCompiler":
        """Set a scalar where a boolean row mask holds for one column."""

        def setter(df: pandas.DataFrame, row_loc: Any, col_loc: Any, item: Any) -> pandas.DataFrame:
            df = df.copy()
            mask = (
                row_loc.squeeze(axis=1)
                if isinstance(row_loc, pandas.DataFrame)
                else row_loc
            )
            df.loc[mask, col_loc] = item
            return df

        return DataFrameDefault.register(setter, fn_name="setitem_bool")(
            self, row_loc=try_cast_to_pandas(row_loc), col_loc=col_loc, item=item
        )

    def rowwise_query(self, expr: str, **kwargs: Any) -> "BaseQueryCompiler":
        """Row-wise ``df.query``; concrete compilers implement the fast path."""
        raise NotImplementedError(
            "Row-wise query execution is not implemented for this backend"
        )

    def apply_on_series(self, func: Any, *args: Any, **kwargs: Any) -> "BaseQueryCompiler":
        assert self.is_series_like()
        return SeriesDefault.register(pandas.Series.apply)(
            self, func=func, *args, **kwargs
        )

    def series_view(self, dtype: Any = None, **kwargs: Any) -> "BaseQueryCompiler":
        """Reinterpret the underlying buffer with a new dtype (the removed
        ``Series.view``; kept for reference name parity)."""

        def view_fn(s: pandas.Series, dtype: Any) -> pandas.Series:
            return pandas.Series(
                s.to_numpy().view(dtype), index=s.index, name=s.name
            )

        return SeriesDefault.register(view_fn, fn_name="series_view")(
            self, dtype=dtype
        )

    def groupby_dtypes(
        self,
        by: Any,
        axis: int = 0,
        groupby_kwargs: Optional[dict] = None,
        agg_args: tuple = (),
        agg_kwargs: Optional[dict] = None,
        drop: bool = False,
    ) -> "BaseQueryCompiler":
        return self.groupby_agg(
            by,
            lambda grp: grp.dtypes,
            axis=axis,
            groupby_kwargs=groupby_kwargs,
            agg_args=agg_args,
            agg_kwargs=agg_kwargs,
            drop=drop,
        )

    def first(self, offset: Any) -> "BaseQueryCompiler":
        """Initial ``offset`` window of a time-indexed frame (the removed
        ``DataFrame.first``; kept for reference name parity)."""

        def first_fn(df: pandas.DataFrame, offset: Any) -> pandas.DataFrame:
            if df.empty:
                return df
            off = pandas.tseries.frequencies.to_offset(offset)
            end = df.index[0] + off
            # Day counted as fixed-width here, matching the legacy behavior
            # (it was a Tick when DataFrame.first existed)
            is_tick = isinstance(off, pandas.tseries.offsets.Tick) or isinstance(
                off, pandas.tseries.offsets.Day
            )
            if is_tick and end in df.index:
                return df.iloc[: df.index.searchsorted(end, side="left")]
            return df.loc[:end]

        return DataFrameDefault.register(first_fn, fn_name="first")(self, offset)

    def last(self, offset: Any) -> "BaseQueryCompiler":
        """Final ``offset`` window of a time-indexed frame (the removed
        ``DataFrame.last``; kept for reference name parity)."""

        def last_fn(df: pandas.DataFrame, offset: Any) -> pandas.DataFrame:
            if df.empty:
                return df
            off = pandas.tseries.frequencies.to_offset(offset)
            start = df.index[-1] - off
            is_tick = isinstance(off, pandas.tseries.offsets.Tick) or isinstance(
                off, pandas.tseries.offsets.Day
            )
            if is_tick and start in df.index:
                return df.iloc[df.index.searchsorted(start, side="right"):]
            return df.loc[start:]

        return DataFrameDefault.register(last_fn, fn_name="last")(self, offset)

    # --- frame metadata-cache introspection (reference: query_compiler.py
    # frame_has_*_cache family; lazy executions report pending metadata) ---

    def frame_has_index_cache(self) -> bool:
        return True

    def frame_has_columns_cache(self) -> bool:
        return True

    def frame_has_dtypes_cache(self) -> bool:
        return True

    def frame_has_materialized_index(self) -> bool:
        return True

    def frame_has_materialized_columns(self) -> bool:
        return True

    def frame_has_materialized_dtypes(self) -> bool:
        return True

    def set_frame_index_cache(self, index: Any) -> None:
        self.index = index

    def set_frame_columns_cache(self, columns: Any) -> None:
        self.columns = columns

    def set_frame_dtypes_cache(self, dtypes: Any) -> None:
        """Lazy-dtype executions adopt an externally-known dtype cache."""

    # --- backend identity + movement (reference: query_compiler.py:243,727) ---

    # backend name -> (storage format, engine) of the execution serving it
    _BACKEND_EXECUTIONS = {"Tpu": ("Tpu", "Jax"), "Pandas": ("Native", "Native")}

    @property
    def storage_format(self) -> str:
        return self._BACKEND_EXECUTIONS.get(
            self.get_backend(), (self.get_backend(), self.get_backend())
        )[0]

    @property
    def engine(self) -> str:
        return self._BACKEND_EXECUTIONS.get(
            self.get_backend(), (self.get_backend(), self.get_backend())
        )[1]

    # --- numpy protocol hooks (reference: query_compiler.py:850,922) ---

    def do_array_ufunc_implementation(
        self, frame: Any, ufunc: Any, method: str, *inputs: Any, **kwargs: Any
    ) -> Any:
        """Backend hook for ``__array_ufunc__`` on API objects: apply the
        ufunc against materialized pandas inputs and re-wrap."""
        cast_inputs = try_cast_to_pandas(inputs, squeeze=True)
        result = getattr(ufunc, method)(*cast_inputs, **kwargs)
        if isinstance(result, (pandas.DataFrame, pandas.Series)):
            if isinstance(result, pandas.Series):
                name = result.name if result.name is not None else MODIN_UNNAMED_SERIES_LABEL
                qc = self.from_pandas(result.to_frame(name))
                qc._shape_hint = "column"
            else:
                qc = self.from_pandas(result)
            return qc
        return result

    def do_array_function_implementation(
        self, frame: Any, func: Any, types: tuple, args: tuple, kwargs: dict
    ) -> Any:
        """Backend hook for ``__array_function__`` (NEP-18) on API objects."""
        cast_args = try_cast_to_pandas(args, squeeze=True)
        cast_kwargs = try_cast_to_pandas(kwargs, squeeze=True)
        result = func(*cast_args, **cast_kwargs)
        if isinstance(result, pandas.Series):
            name = result.name if result.name is not None else MODIN_UNNAMED_SERIES_LABEL
            qc = self.from_pandas(result.to_frame(name))
            qc._shape_hint = "column"
            return qc
        if isinstance(result, pandas.DataFrame):
            return self.from_pandas(result)
        return result

    def move_to(self, target_backend: str) -> "BaseQueryCompiler":
        from modin_tpu.core.storage_formats.base.query_compiler_caster import (
            qc_class_for_backend,
        )

        target_cls = qc_class_for_backend(target_backend)
        if isinstance(self, target_cls):
            return self
        return target_cls.move_from(self)

    @classmethod
    def move_from(cls, source_qc: "BaseQueryCompiler") -> "BaseQueryCompiler":
        if isinstance(source_qc, cls):
            return source_qc
        return cls.from_pandas(source_qc.to_pandas())

    def is_monotonic_increasing(self) -> bool:
        return SeriesDefault.register(pandas.Series.is_monotonic_increasing)(self)

    def is_monotonic_decreasing(self) -> bool:
        return SeriesDefault.register(pandas.Series.is_monotonic_decreasing)(self)

    def first_valid_index(self) -> Any:
        return self.to_pandas().first_valid_index()

    def last_valid_index(self) -> Any:
        return self.to_pandas().last_valid_index()

    def has_multiindex(self, axis: int = 0) -> bool:
        return isinstance(self.index if axis == 0 else self.columns, pandas.MultiIndex)

    # ------------------------------------------------------------------ #
    # Groupby (single generic entry point; string-kernel fast paths live in
    # concrete compilers)
    # ------------------------------------------------------------------ #

    def groupby_agg(
        self,
        by: Any,
        agg_func: Any,
        axis: int = 0,
        groupby_kwargs: Optional[dict] = None,
        agg_args: tuple = (),
        agg_kwargs: Optional[dict] = None,
        how: str = "axis_wise",
        drop: bool = False,
        series_groupby: bool = False,
        selection: Any = None,
    ) -> "BaseQueryCompiler":
        df = self.to_pandas()
        if series_groupby and selection is None:
            df = df.squeeze(axis=1)
        pandas_by = try_cast_to_pandas(by, squeeze=True)
        groupby_kwargs = dict(groupby_kwargs or {})
        agg_kwargs = dict(agg_kwargs or {})
        ErrorMessage.default_to_pandas("`groupby_agg`")
        grp = df.groupby(by=pandas_by, **groupby_kwargs)
        if selection is not None:
            grp = grp[selection]
        if callable(agg_func):
            result = agg_func(grp, *agg_args, **agg_kwargs)
        elif isinstance(agg_func, str):
            result = getattr(grp, agg_func)(*agg_args, **agg_kwargs)
        else:
            result = grp.agg(agg_func, *agg_args, **agg_kwargs)
        was_series = isinstance(result, pandas.Series)
        if was_series:
            name = result.name if result.name is not None else MODIN_UNNAMED_SERIES_LABEL
            result = result.to_frame(name)
        qc = self.from_pandas(result, type(self._modin_frame) if self._modin_frame is not None else None)
        if was_series:
            qc._shape_hint = "column"
        return qc

    def groupby_transform(
        self,
        by: Any,
        agg_func: Any,
        groupby_kwargs: Optional[dict] = None,
        drop: bool = False,
        series_groupby: bool = False,
        selection: Any = None,
    ) -> "BaseQueryCompiler":
        """Row-shaped groupby transform (``grp.transform(func)``)."""
        transformer = lambda grp: grp.transform(agg_func)  # noqa: E731
        transformer._row_shaped_groupby = True
        return self.groupby_agg(
            by,
            transformer,
            groupby_kwargs=groupby_kwargs,
            drop=drop,
            series_groupby=series_groupby,
            selection=selection,
        )

    # ------------------------------------------------------------------ #
    # Merge / join
    # ------------------------------------------------------------------ #

    def merge(self, right: "BaseQueryCompiler", **kwargs: Any) -> "BaseQueryCompiler":
        return BinaryDefault.register(pandas.DataFrame.merge)(self, right, **kwargs)

    def merge_asof(self, right: "BaseQueryCompiler", **kwargs: Any) -> "BaseQueryCompiler":
        return BinaryDefault.register(pandas.merge_asof, fn_name="merge_asof")(
            self, right, **kwargs
        )

    def join(self, right: Any, **kwargs: Any) -> "BaseQueryCompiler":
        if isinstance(right, BaseQueryCompiler):
            right = right.to_pandas()
        elif isinstance(right, (list, tuple)):
            right = [
                r.to_pandas() if isinstance(r, BaseQueryCompiler) else r for r in right
            ]
        return DataFrameDefault.register(pandas.DataFrame.join)(self, right, **kwargs)

    # ------------------------------------------------------------------ #
    # Misc ops with non-trivial arg handling
    # ------------------------------------------------------------------ #

    def fillna(self, **kwargs: Any) -> "BaseQueryCompiler":
        squeeze_self = kwargs.pop("squeeze_self", False)
        squeeze_value = kwargs.pop("squeeze_value", False)

        def fillna_fn(df: pandas.DataFrame, **kw: Any) -> Any:
            if squeeze_self:
                df = df.squeeze(axis=1)
            value = kw.get("value")
            if squeeze_value and isinstance(value, pandas.DataFrame):
                kw["value"] = value.squeeze(axis=1)
            return df.fillna(**kw)

        kwargs["value"] = try_cast_to_pandas(kwargs.get("value"))
        return DataFrameDefault.register(fillna_fn, fn_name="fillna")(self, **kwargs)

    def apply(
        self,
        func: Any,
        axis: int = 0,
        raw: bool = False,
        result_type: Any = None,
        args: tuple = (),
        **kwargs: Any,
    ) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.apply)(
            self, func=func, axis=axis, raw=raw, result_type=result_type,
            args=args, **kwargs,
        )

    def explode(self, column: Any, ignore_index: bool = False) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.explode)(
            self, column, ignore_index=ignore_index
        )

    def series_update(self, other: Any, **kwargs: Any) -> "BaseQueryCompiler":
        def update_fn(s: pandas.Series, other: Any) -> pandas.Series:
            s = s.copy()
            s.update(other.squeeze(axis=1) if isinstance(other, pandas.DataFrame) else other)
            return s

        return BinaryDefault.register(update_fn, squeeze_self=True, fn_name="series_update")(
            self, other
        )

    def df_update(self, other: Any, **kwargs: Any) -> "BaseQueryCompiler":
        def update_fn(df: pandas.DataFrame, other: Any, **kw: Any) -> pandas.DataFrame:
            df = df.copy()
            df.update(other, **kw)
            return df

        return BinaryDefault.register(update_fn, fn_name="df_update")(self, other, **kwargs)

    def clip(self, lower: Any, upper: Any, **kwargs: Any) -> "BaseQueryCompiler":
        lower = try_cast_to_pandas(lower, squeeze=True)
        upper = try_cast_to_pandas(upper, squeeze=True)
        return DataFrameDefault.register(pandas.DataFrame.clip)(
            self, lower, upper, **kwargs
        )

    def where(self, cond: Any, other: Any, **kwargs: Any) -> "BaseQueryCompiler":
        cond = try_cast_to_pandas(cond)
        other = try_cast_to_pandas(other)
        return DataFrameDefault.register(pandas.DataFrame.where)(
            self, cond, other, **kwargs
        )

    def get_dummies(self, columns: Any, **kwargs: Any) -> "BaseQueryCompiler":
        def get_dummies_fn(df: pandas.DataFrame, columns: Any, **kw: Any) -> pandas.DataFrame:
            return pandas.get_dummies(df, columns=columns, **kw)

        return DataFrameDefault.register(get_dummies_fn, fn_name="get_dummies")(
            self, columns, **kwargs
        )

    def searchsorted(self, **kwargs: Any) -> "BaseQueryCompiler":
        def searchsorted_fn(s: pandas.Series, **kw: Any) -> pandas.Series:
            return pandas.Series(s.searchsorted(**kw))

        return SeriesDefault.register(searchsorted_fn, fn_name="searchsorted")(self, **kwargs)

    def unique(self, **kwargs: Any) -> "BaseQueryCompiler":
        def unique_fn(s: pandas.Series, **kw: Any) -> pandas.Series:
            return pandas.Series(s.unique(**kw))

        return SeriesDefault.register(unique_fn, fn_name="unique")(self, **kwargs)

    def repeat(self, repeats: Any) -> "BaseQueryCompiler":
        return SeriesDefault.register(pandas.Series.repeat)(self, repeats=repeats)

    def isin(self, values: Any, ignore_indices: bool = False, **kwargs: Any) -> "BaseQueryCompiler":
        if isinstance(values, type(self)) and ignore_indices:
            values = values.to_pandas().squeeze(axis=1).tolist()
        else:
            values = try_cast_to_pandas(values, squeeze=True)
        return DataFrameDefault.register(pandas.DataFrame.isin)(self, values=values)

    def case_when(self, caselist: list) -> "BaseQueryCompiler":
        caselist = [
            tuple(
                data.to_pandas().squeeze(axis=1) if isinstance(data, type(self)) else data
                for data in case_tuple
            )
            for case_tuple in caselist
        ]
        return SeriesDefault.register(pandas.Series.case_when)(self, caselist=caselist)

    def compare(self, other: Any, **kwargs: Any) -> "BaseQueryCompiler":
        return BinaryDefault.register(pandas.DataFrame.compare)(self, other=other, **kwargs)

    def expanding_aggregate(self, axis, expanding_args, func, *args, **kwargs):
        return ExpandingDefault.register(
            pandas.core.window.expanding.Expanding.aggregate
        )(self, expanding_args, func, *args, **kwargs)

    # window generic
    def rolling_aggregate(self, fold_axis, rolling_kwargs, func, *args, **kwargs):
        return RollingDefault.register(
            pandas.core.window.rolling.Rolling.aggregate
        )(self, rolling_kwargs, func, *args, **kwargs)

    def groupby_window(
        self, by, kind, window_kwargs, agg_func, groupby_kwargs, agg_args,
        agg_kwargs, drop=False, selection=None, series_groupby=False,
    ):
        """Windowed aggregation over groups: ``grp.<kind>(**kw).<agg>()``
        for kind in rolling/expanding/ewm (reference modin/pandas/window.py
        RollingGroupby; one generic seam here since all three window
        families share the groupby shape)."""
        df = self.to_pandas()
        if series_groupby and selection is None:
            df = df.squeeze(axis=1)
        pandas_by = try_cast_to_pandas(by, squeeze=True)
        ErrorMessage.default_to_pandas(f"`GroupBy.{kind}.{agg_func}`")
        grp = df.groupby(by=pandas_by, **dict(groupby_kwargs or {}))
        if selection is not None:
            grp = grp[selection]
        win = getattr(grp, kind)(**window_kwargs)
        if isinstance(agg_func, str):
            result = getattr(win, agg_func)(*agg_args, **dict(agg_kwargs or {}))
        else:
            result = agg_func(win, *agg_args, **dict(agg_kwargs or {}))
        was_series = isinstance(result, pandas.Series)
        if was_series:
            name = result.name if result.name is not None else MODIN_UNNAMED_SERIES_LABEL
            result = result.to_frame(name)
        qc = self.from_pandas(result, type(self._modin_frame) if self._modin_frame is not None else None)
        if was_series:
            qc._shape_hint = "column"
        return qc

    def groupby_rolling(self, by, agg_func, axis, groupby_kwargs, rolling_kwargs, agg_args, agg_kwargs, drop=False, selection=None, series_groupby=False):
        return self.groupby_window(
            by, "rolling", rolling_kwargs, agg_func, groupby_kwargs,
            agg_args, agg_kwargs, drop=drop, selection=selection,
            series_groupby=series_groupby,
        )

    # ------------------------------------------------------------------ #
    # String free-function conversions (series-level)
    # ------------------------------------------------------------------ #

    def to_datetime(self, *args: Any, **kwargs: Any) -> "BaseQueryCompiler":
        return SeriesDefault.register(pandas.to_datetime, fn_name="to_datetime")(
            self, *args, **kwargs
        )

    def to_numeric(self, *args: Any, **kwargs: Any) -> "BaseQueryCompiler":
        return SeriesDefault.register(pandas.to_numeric, fn_name="to_numeric")(
            self, *args, **kwargs
        )

    def to_timedelta(self, *args: Any, **kwargs: Any) -> "BaseQueryCompiler":
        return SeriesDefault.register(pandas.to_timedelta, fn_name="to_timedelta")(
            self, *args, **kwargs
        )

    # dt extraction needing the index rather than values
    def dt_nanoseconds(self) -> "BaseQueryCompiler":
        return DateTimeDefault.register(property(lambda dt: dt.nanoseconds), fn_name="nanoseconds")(self)

    def unary_math(self, op_name: str) -> "BaseQueryCompiler":
        """Elementwise numpy-style math (sqrt/exp/log/...) over the frame."""
        ufunc = getattr(np, op_name)
        return DataFrameDefault.register(
            lambda df: pandas.DataFrame(
                ufunc(df.to_numpy()), index=df.index, columns=df.columns
            ),
            fn_name=op_name,
        )(self)

    def describe(self, percentiles: Any = None, include: Any = None, exclude: Any = None) -> "BaseQueryCompiler":
        return DataFrameDefault.register(pandas.DataFrame.describe)(
            self, percentiles=percentiles, include=include, exclude=exclude
        )

    def write_csv(self, **kwargs: Any) -> Any:
        return self.to_pandas().to_csv(**kwargs)

    # free any deferred results; used by tests
    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} shape_hint={self._shape_hint}>"


# ---------------------------------------------------------------------- #
# Programmatic defaults: the long tail of the ~460-method surface.
# Each entry becomes `BaseQueryCompiler.<name> = Builder.register(<kernel>)`.
# Concrete compilers override the hot subset (see TpuQueryCompiler).
# ---------------------------------------------------------------------- #

def _register_defaults() -> None:
    binary_methods = [
        "add", "radd", "sub", "rsub", "mul", "rmul", "truediv", "rtruediv",
        "floordiv", "rfloordiv", "mod", "rmod", "pow", "rpow",
        "eq", "ne", "lt", "le", "gt", "ge",
        "__and__", "__or__", "__xor__", "__rand__", "__ror__", "__rxor__",
    ]
    for qc_name in binary_methods:
        fn = getattr(pandas.DataFrame, qc_name, None)
        if fn is not None:
            setattr(BaseQueryCompiler, qc_name, BinaryDefault.register(fn))

    df_methods = {
        # reductions
        "sum": "sum", "prod": "prod", "count": "count", "mean": "mean",
        "median": "median", "std": "std", "var": "var", "sem": "sem",
        "skew": "skew", "kurt": "kurt", "min": "min", "max": "max",
        "any": "any", "all": "all", "idxmin": "idxmin", "idxmax": "idxmax",
        "nunique": "nunique", "memory_usage": "memory_usage",
        # maps
        "abs": "abs", "round": "round", "replace": "replace",
        "negative": "__neg__", "invert": "__invert__",
        "ffill": "ffill", "bfill": "bfill",
        "isna": "isna", "notna": "notna", "convert_dtypes": "convert_dtypes",
        "infer_objects": "infer_objects", "map": "map",
        # cumulative
        "cumsum": "cumsum", "cummax": "cummax", "cummin": "cummin",
        "cumprod": "cumprod",
        # reshaping / misc
        "astype": "astype", "diff": "diff", "shift": "shift", "rank": "rank",
        "quantile": "quantile", "nlargest": "nlargest", "nsmallest": "nsmallest",
        "duplicated": "duplicated", "drop_duplicates": "drop_duplicates",
        "stack": "stack", "unstack": "unstack", "melt": "melt",
        "pivot": "pivot", "corr": "corr", "cov": "cov",
        "mode": "mode", "dropna": "dropna", "eval": "eval",
        "query": "query", "sample": "sample", "asfreq": "asfreq",
        "interpolate": "interpolate", "kurtosis": "kurt",
        "truncate": "truncate", "droplevel": "droplevel",
        "swaplevel": "swaplevel", "reorder_levels": "reorder_levels",
        "to_period": "to_period", "to_timestamp": "to_timestamp",
        "tz_convert": "tz_convert", "tz_localize": "tz_localize",
        "pct_change": "pct_change", "at_time": "at_time",
        "between_time": "between_time",
        "add_prefix": "add_prefix", "add_suffix": "add_suffix",
    }
    for qc_name, pandas_name in df_methods.items():
        existing = getattr(BaseQueryCompiler, qc_name, None)
        if existing is None:
            fn = getattr(pandas.DataFrame, pandas_name, None)
            if fn is None:
                continue
            existing = DataFrameDefault.register(fn)
            setattr(BaseQueryCompiler, qc_name, existing)
        if (
            qc_name == pandas_name
            and not pandas_name.startswith("_")
            and getattr(existing, "_pandas_signature_default", False)
        ):
            # generated from the pandas callable itself -> signature-safe to
            # route the API fallback through the named QC method (dispatch
            # re-verifies the marker on the *resolved* method, so a backend
            # override with a normalized signature is never mis-bound)
            DATAFRAME_QC_ROUTES.setdefault(pandas_name, qc_name)

    # ops that must run against the squeezed Series
    BaseQueryCompiler.series_value_counts = SeriesDefault.register(
        pandas.Series.value_counts
    )
    BaseQueryCompiler.series_argsort = SeriesDefault.register(pandas.Series.argsort)
    BaseQueryCompiler.series_between = SeriesDefault.register(pandas.Series.between)
    BaseQueryCompiler.series_autocorr = SeriesDefault.register(pandas.Series.autocorr)
    BaseQueryCompiler.series_corr = SeriesDefault.register(pandas.Series.corr)
    BaseQueryCompiler.series_cov = SeriesDefault.register(pandas.Series.cov)
    BaseQueryCompiler.dot = BinaryDefault.register(pandas.DataFrame.dot)
    BaseQueryCompiler.series_dot = BinaryDefault.register(
        pandas.Series.dot, squeeze_self=True, fn_name="series_dot"
    )
    BaseQueryCompiler.align = BinaryDefault.register(pandas.DataFrame.align)
    BaseQueryCompiler.combine = BinaryDefault.register(pandas.DataFrame.combine)
    BaseQueryCompiler.combine_first = BinaryDefault.register(
        pandas.DataFrame.combine_first
    )

    # str accessor surface
    str_methods = [
        "capitalize", "casefold", "cat", "center", "contains", "count",
        "decode", "encode", "endswith", "extract", "extractall", "find",
        "findall", "fullmatch", "get", "get_dummies", "index", "join", "len",
        "ljust", "lower", "lstrip", "match", "normalize", "pad", "partition",
        "removeprefix", "removesuffix", "repeat", "replace", "rfind", "rindex",
        "rjust", "rpartition", "rsplit", "rstrip", "slice", "slice_replace",
        "split", "startswith", "strip", "swapcase", "title", "translate",
        "upper", "wrap", "zfill", "isalnum", "isalpha", "isdecimal", "isdigit",
        "islower", "isnumeric", "isspace", "istitle", "isupper",
    ]
    str_cls = pandas.core.strings.accessor.StringMethods
    for name in str_methods:
        target = getattr(str_cls, name, None)
        if target is None:
            continue
        setattr(BaseQueryCompiler, f"str_{name}", StrDefault.register(target, fn_name=name))
    BaseQueryCompiler.str___getitem__ = StrDefault.register(
        str_cls.__getitem__, fn_name="__getitem__"
    )

    # dt accessor surface: properties + methods
    dt_cls = pandas.core.indexes.accessors.CombinedDatetimelikeProperties
    dt_props = [
        "date", "time", "timetz", "year", "month", "day", "hour", "minute",
        "second", "microsecond", "nanosecond", "dayofweek", "day_of_week",
        "weekday", "dayofyear", "day_of_year", "quarter", "is_month_start",
        "is_month_end", "is_quarter_start", "is_quarter_end", "is_year_start",
        "is_year_end", "is_leap_year", "daysinmonth", "days_in_month", "tz",
        "freq", "unit", "days", "seconds", "microseconds", "nanoseconds",
        "components", "qyear", "start_time", "end_time",
    ]
    for name in dt_props:
        setattr(
            BaseQueryCompiler,
            f"dt_{name}",
            SeriesDefault.register(
                (lambda nm: (lambda s: getattr(s.dt, nm)))(name), fn_name=name
            ),
        )
    dt_methods = [
        "to_period", "to_pydatetime", "tz_localize", "tz_convert", "normalize",
        "strftime", "round", "floor", "ceil", "month_name", "day_name",
        "total_seconds", "to_pytimedelta", "asfreq", "isocalendar", "to_timestamp",
    ]
    for name in dt_methods:
        setattr(
            BaseQueryCompiler,
            f"dt_{name}",
            SeriesDefault.register(
                (lambda nm: (lambda s, *a, **k: getattr(s.dt, nm)(*a, **k)))(name),
                fn_name=name,
            ),
        )

    # cat accessor
    BaseQueryCompiler.cat_codes = SeriesDefault.register(
        lambda s: s.cat.codes, fn_name="codes"
    )
    for name in [
        "add_categories", "remove_categories", "remove_unused_categories",
        "rename_categories", "reorder_categories", "set_categories",
        "as_ordered", "as_unordered",
    ]:
        setattr(
            BaseQueryCompiler,
            f"cat_{name}",
            SeriesDefault.register(
                (lambda nm: (lambda s, *a, **k: getattr(s.cat, nm)(*a, **k)))(name),
                fn_name=name,
            ),
        )

    # rolling/expanding/resample aggregations
    for name in [
        "count", "sum", "mean", "median", "var", "std", "min", "max", "skew",
        "kurt", "sem", "quantile", "apply", "rank", "corr", "cov",
    ]:
        setattr(BaseQueryCompiler, f"rolling_{name}", RollingDefault.register(name))
        setattr(BaseQueryCompiler, f"expanding_{name}", ExpandingDefault.register(name))
    for name in ["mean", "sum", "var", "std", "corr", "cov", "aggregate"]:
        setattr(BaseQueryCompiler, f"ewm_{name}", EwmDefault.register(name))
    for name in [
        "count", "sum", "mean", "median", "var", "std", "min", "max", "sem",
        "first", "last", "ohlc", "prod", "size", "nunique", "quantile",
        "agg", "aggregate", "apply", "transform", "ffill", "bfill", "nearest",
        "asfreq", "interpolate",
    ]:
        setattr(BaseQueryCompiler, f"resample_{name}", ResampleDefault.register(name))

    # named groupby aggregations (used when api wants direct dispatch)
    for name in [
        "sum", "count", "size", "mean", "min", "max", "prod", "any", "all",
        "median", "std", "var", "sem", "skew", "nunique", "first", "last",
        "head", "tail", "ngroup", "cumsum", "cumprod", "cummax", "cummin",
        "cumcount", "rank", "shift", "diff", "pct_change", "quantile",
        "fillna", "ffill", "bfill", "idxmin", "idxmax", "corr", "cov",
        "value_counts", "ohlc", "sample", "nth", "unique",
        "get_group", "nlargest", "nsmallest", "take", "hist", "boxplot",
    ]:
        setattr(BaseQueryCompiler, f"groupby_{name}", GroupByDefault.register(name))

    _register_long_tail()
    _register_full_api_surface()


# Names the sweep must not route through the QC: data-exchange/iteration/
# accessor factories the API layer owns, writers, and methods whose QC
# counterpart has a normalized (non-pandas) signature.
_SWEEP_EXCLUDE = frozenset(
    [
        # accessor / lazy-handle factories (API constructs the handle)
        "groupby", "rolling", "expanding", "ewm", "resample", "plot", "hist",
        "boxplot", "style", "str", "dt", "cat", "sparse", "list", "struct",
        # iteration / identity / conversion the API layer owns
        "items", "iterrows", "itertuples", "keys", "bool", "info", "copy",
        "pipe", "pop", "squeeze", "transpose", "swapaxes", "set_flags",
        "__iter__",
        # explicit QC methods with normalized signatures (API wires these)
        "drop", "fillna", "insert", "merge", "join", "apply", "where", "mask",
        "clip", "isin", "sort_index", "sort_values", "reindex", "reset_index",
        "set_index", "describe", "explode", "update", "compare", "align",
        "combine", "combine_first", "dot", "get", "filter", "take", "xs",
        "reindex_like", "rename", "rename_axis", "set_axis", "agg",
        "aggregate", "applymap", "assign", "equals", "head", "tail", "nth",
        "first", "last", "abs",
    ]
)


def _register_full_api_surface() -> None:
    """Sweep the public pandas.DataFrame/Series surfaces: every remaining
    callable gets a named, generated QC default (``<name>`` for frame ops,
    ``series_<name>`` for series ops) plus a routing-table entry so the API
    fallback path dispatches through the QC by name (ref: the ~460-method
    surface of base/query_compiler.py:162)."""
    import functools as _functools
    import inspect as _inspect

    for name in dir(pandas.DataFrame):
        if name.startswith("_") or name in _SWEEP_EXCLUDE or name.startswith("to_"):
            continue
        raw = _inspect.getattr_static(pandas.DataFrame, name)
        if isinstance(raw, (property, _functools.cached_property)):
            continue
        attr = getattr(pandas.DataFrame, name, None)
        if not callable(attr) or isinstance(raw, (classmethod, staticmethod)):
            continue
        if name in DATAFRAME_QC_ROUTES:
            continue
        if getattr(BaseQueryCompiler, name, None) is None:
            setattr(BaseQueryCompiler, name, DataFrameDefault.register(attr))
            DATAFRAME_QC_ROUTES[name] = name
        # an existing explicit def with a custom signature is NOT routed

    for name in dir(pandas.Series):
        if name.startswith("_") or name in _SWEEP_EXCLUDE or name.startswith("to_"):
            continue
        raw = _inspect.getattr_static(pandas.Series, name)
        if isinstance(raw, property):
            continue
        attr = getattr(pandas.Series, name, None)
        if not callable(attr) or isinstance(raw, (classmethod, staticmethod)):
            continue
        qc_name = f"series_{name}"
        existing = getattr(BaseQueryCompiler, qc_name, None)
        if existing is None:
            setattr(
                BaseQueryCompiler,
                qc_name,
                SeriesDefault.register(attr, fn_name=qc_name),
            )
        SERIES_QC_ROUTES.setdefault(name, qc_name)

    # series routes for names covered by pre-existing series_* registrations
    # generated from the matching pandas.Series callable
    for name in ("value_counts", "between", "autocorr", "corr", "cov"):
        SERIES_QC_ROUTES.setdefault(name, f"series_{name}")


def _register_long_tail() -> None:
    """The rest of the reference QC surface (ref base/query_compiler.py:162):
    binary comparisons in Series form, reshape free functions, Arrow list/
    struct accessors, win_type rolling, and resample shape variants.  All
    default-to-pandas; concrete compilers may override any of them, and the
    caster/extensions/tracing layers observe them by name."""
    # Series-form binary comparisons (ref: series_eq..series_ge) + divmod
    for name in ("eq", "ne", "lt", "le", "gt", "ge"):
        setattr(
            BaseQueryCompiler,
            f"series_{name}",
            BinaryDefault.register(
                getattr(pandas.Series, name), squeeze_self=True, fn_name=f"series_{name}"
            ),
        )
    BaseQueryCompiler.divmod = BinaryDefault.register(
        pandas.Series.divmod, squeeze_self=True, fn_name="divmod"
    )
    BaseQueryCompiler.rdivmod = BinaryDefault.register(
        pandas.Series.rdivmod, squeeze_self=True, fn_name="rdivmod"
    )
    BaseQueryCompiler.equals = BinaryDefault.register(pandas.DataFrame.equals)
    BaseQueryCompiler.corrwith = BinaryDefault.register(pandas.DataFrame.corrwith)
    BaseQueryCompiler.mask = BinaryDefault.register(pandas.DataFrame.mask)
    BaseQueryCompiler.series_mask = BinaryDefault.register(
        pandas.Series.mask, squeeze_self=True, fn_name="series_mask"
    )

    # reshape / free-function surface applied against self
    BaseQueryCompiler.pivot_table = DataFrameDefault.register(
        pandas.DataFrame.pivot_table
    )
    BaseQueryCompiler.cut = SeriesDefault.register(
        lambda s, **kwargs: pandas.cut(s, **kwargs), fn_name="cut"
    )
    BaseQueryCompiler.qcut = SeriesDefault.register(
        lambda s, **kwargs: pandas.qcut(s, **kwargs), fn_name="qcut"
    )
    BaseQueryCompiler.merge_ordered = BinaryDefault.register(
        lambda df, right, **kwargs: pandas.merge_ordered(df, right, **kwargs),
        fn_name="merge_ordered",
    )
    BaseQueryCompiler.wide_to_long = DataFrameDefault.register(
        lambda df, **kwargs: pandas.wide_to_long(df, **kwargs), fn_name="wide_to_long"
    )
    BaseQueryCompiler.lreshape = DataFrameDefault.register(
        lambda df, groups, **kwargs: pandas.lreshape(df, groups, **kwargs),
        fn_name="lreshape",
    )

    # conversions / misc parity names
    BaseQueryCompiler.dataframe_to_dict = DataFrameDefault.register(
        pandas.DataFrame.to_dict, fn_name="dataframe_to_dict"
    )
    BaseQueryCompiler.series_to_dict = SeriesDefault.register(
        pandas.Series.to_dict, fn_name="series_to_dict"
    )
    BaseQueryCompiler.to_list = SeriesDefault.register(
        pandas.Series.to_list, fn_name="to_list"
    )
    BaseQueryCompiler.argsort = SeriesDefault.register(pandas.Series.argsort)
    BaseQueryCompiler.conj = DataFrameDefault.register(
        lambda df: pandas.DataFrame(
            np.conj(df.to_numpy()), index=df.index, columns=df.columns
        ),
        fn_name="conj",
    )
    BaseQueryCompiler.delitem = DataFrameDefault.register(
        lambda df, key: df.drop(columns=[key]), fn_name="delitem"
    )
    BaseQueryCompiler.sizeof = DataFrameDefault.register(
        lambda df: df.memory_usage(index=True, deep=True).sum(), fn_name="sizeof"
    )
    BaseQueryCompiler.quantile_for_single_value = DataFrameDefault.register(
        pandas.DataFrame.quantile, fn_name="quantile_for_single_value"
    )
    BaseQueryCompiler.quantile_for_list_of_values = DataFrameDefault.register(
        pandas.DataFrame.quantile, fn_name="quantile_for_list_of_values"
    )

    # dt unit conversion (pandas 2+ non-nano support)
    BaseQueryCompiler.dt_as_unit = SeriesDefault.register(
        lambda s, *a, **k: s.dt.as_unit(*a, **k), fn_name="as_unit"
    )

    # Arrow-backed list/struct accessors (ref: list_*, struct_*)
    BaseQueryCompiler.list_flatten = ListDefault.register("flatten", fn_name="flatten")
    BaseQueryCompiler.list_len = ListDefault.register("len", fn_name="len")
    BaseQueryCompiler.list___getitem__ = ListDefault.register(
        "__getitem__", fn_name="__getitem__"
    )
    BaseQueryCompiler.struct_explode = StructDefault.register(
        "explode", fn_name="explode"
    )
    BaseQueryCompiler.struct_field = StructDefault.register("field", fn_name="field")
    BaseQueryCompiler.struct_dtypes = StructDefault.register(
        lambda acc: acc.dtypes, fn_name="dtypes"
    )

    # win_type rolling (pandas Window object; kwargs carry win_type)
    for name in ("mean", "sum", "var", "std"):
        setattr(BaseQueryCompiler, f"window_{name}", RollingDefault.register(name))

    # resample shape variants (ref: resample_agg_df/ser, app_df/ser, ohlc_*)
    BaseQueryCompiler.resample_agg_df = ResampleDefault.register(
        "aggregate", fn_name="agg_df"
    )
    BaseQueryCompiler.resample_agg_ser = ResampleDefault.register(
        "aggregate", squeeze_self=True, fn_name="agg_ser"
    )
    BaseQueryCompiler.resample_app_df = ResampleDefault.register(
        "apply", fn_name="app_df"
    )
    BaseQueryCompiler.resample_app_ser = ResampleDefault.register(
        "apply", squeeze_self=True, fn_name="app_ser"
    )
    BaseQueryCompiler.resample_ohlc_df = ResampleDefault.register(
        "ohlc", fn_name="ohlc_df"
    )
    BaseQueryCompiler.resample_ohlc_ser = ResampleDefault.register(
        "ohlc", squeeze_self=True, fn_name="ohlc_ser"
    )
    BaseQueryCompiler.resample_fillna = ResampleDefault.register(
        lambda r, method, limit=None: r.nearest(limit=limit)
        if method == "nearest"
        else getattr(r, method)(limit=limit),
        fn_name="fillna",
    )
    BaseQueryCompiler.resample_get_group = ResampleDefault.register(
        "get_group", fn_name="get_group"
    )
    BaseQueryCompiler.resample_pipe = ResampleDefault.register("pipe", fn_name="pipe")


_register_defaults()
