"""``NativeQueryCompiler`` — zero-distribution, in-process pandas backend.

Reference design: /root/reference/modin/core/storage_formats/pandas/native_query_compiler.py:93.
Used as the small-data fast path (device dispatch overhead dominates under
~10^5 rows) and as the host endpoint of device<->host backend switching.
"""

from __future__ import annotations

from typing import Any, Optional

import pandas

from modin_tpu.config import NativePandasMaxRows, NativePandasTransferThreshold
from modin_tpu.core.storage_formats.base.query_compiler import (
    BaseQueryCompiler,
    QCCoercionCost,
)
from modin_tpu.utils import MODIN_UNNAMED_SERIES_LABEL


class NativeQueryCompiler(BaseQueryCompiler):
    """A query compiler holding one plain ``pandas.DataFrame`` in-process."""

    storage_format = property(lambda self: "Native")
    engine = property(lambda self: "Native")

    def __init__(self, pandas_frame: pandas.DataFrame, shape_hint: Optional[str] = None):
        assert isinstance(pandas_frame, pandas.DataFrame), type(pandas_frame)
        self._pandas_frame = pandas_frame
        self._shape_hint = shape_hint
        if shape_hint is None and len(pandas_frame.columns) == 1:
            if pandas_frame.columns[0] == MODIN_UNNAMED_SERIES_LABEL:
                self._shape_hint = "column"

    # -- data exchange ------------------------------------------------- #

    @classmethod
    def from_pandas(cls, df: pandas.DataFrame, data_cls: Any = None) -> "NativeQueryCompiler":
        return cls(df)

    def to_pandas(self) -> pandas.DataFrame:
        return self._pandas_frame.copy()

    def copy(self) -> "NativeQueryCompiler":
        return type(self)(self._pandas_frame, self._shape_hint)

    def free(self) -> None:
        self._pandas_frame = None

    # -- metadata ------------------------------------------------------ #

    def get_index(self) -> pandas.Index:
        return self._pandas_frame.index

    def get_columns(self) -> pandas.Index:
        return self._pandas_frame.columns

    def _set_index(self, idx: pandas.Index) -> None:
        self._pandas_frame = self._pandas_frame.set_axis(idx, axis=0)

    def _set_columns(self, cols: pandas.Index) -> None:
        self._pandas_frame = self._pandas_frame.set_axis(cols, axis=1)

    index = property(get_index, _set_index)
    columns = property(get_columns, _set_columns)

    @property
    def dtypes(self) -> pandas.Series:
        return self._pandas_frame.dtypes

    def get_axis_len(self, axis: int) -> int:
        return self._pandas_frame.shape[1 if axis else 0]

    # -- cost model (reference: native_query_compiler.py:234-260) ------- #

    def stay_cost(self, api_cls_name, operation, arguments) -> Optional[int]:
        if len(self._pandas_frame) > NativePandasMaxRows.get():
            return QCCoercionCost.COST_HIGH
        return QCCoercionCost.COST_ZERO

    def move_to_cost(self, other_qc_type, api_cls_name, operation, arguments) -> Optional[int]:
        if type(self) is other_qc_type:
            return QCCoercionCost.COST_ZERO
        nrows = len(self._pandas_frame)
        if nrows > NativePandasTransferThreshold.get():
            return QCCoercionCost.COST_HIGH
        if nrows > NativePandasMaxRows.get():
            return QCCoercionCost.COST_MEDIUM
        return QCCoercionCost.COST_LOW

    @classmethod
    def move_to_me_cost(cls, other_qc, api_cls_name, operation, arguments) -> Optional[int]:
        if isinstance(other_qc, cls):
            return QCCoercionCost.COST_ZERO
        try:
            # small frames are exactly what in-process pandas is best at
            if other_qc.get_axis_len(0) <= NativePandasMaxRows.get():
                return QCCoercionCost.COST_ZERO
        except Exception:  # graftlint: disable=EXC-HYGIENE -- host-only cost estimate on the in-process backend; advisory
            pass
        return QCCoercionCost.COST_MEDIUM
