"""modin_tpu subpackage."""
