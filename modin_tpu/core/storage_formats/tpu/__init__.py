"""modin_tpu subpackage."""
